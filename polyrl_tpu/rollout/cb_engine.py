"""Continuous-batching rollout engine over a paged KV pool.

TPU-native equivalent of SGLang's continuous-batching scheduler + paged KV
runtime that the reference builds its rollout layer on (SURVEY.md §2.2
native-census row 1; queue-depth telemetry patches.py:423-425; abort
sglang_http_async_engine.py:286-298). Design:

- ONE compiled decode step for every request mix: a fixed array of ``S``
  slots; per-slot sampling params (temperature/top-p/top-k/stop tokens) are
  traced arrays, so admission never recompiles (contrast the bucketed v0
  ``StepDecoder`` which compiles per sampling group).
- Paged KV: slots own page lists from a shared pool
  (``decoder.make_paged_pools``); attention is
  ``ops.paged_attention`` (Pallas on TPU). No shape buckets in decode.
  Dispatches with live GRPO groups route through the two-phase GROUPED
  kernel (``grouped_paged_attention``): one HBM stream of the group's
  shared prompt KV serves every sibling per decode step, suffixes merge
  via the flash LSE — the group tables ride each dispatch as traced data
  (ARCHITECTURE.md "Shared-prefix decode attention").
- Admission: FUSED async prefill (compiled per prompt bucket) — one packed
  int32 control upload per request; the prefill inserts the slot into the
  device-resident control state and the first token joins the deferred
  emission queue. No host round trip per admission.
- Decode: the control state lives on device and the step ADVANCES it there;
  dispatches stay `pipeline_depth` ahead while a dedicated FETCHER THREAD
  owns the blocking device->host output transfer, batching every queued
  dispatch output into one ``device_get`` — so the loop keeps the device
  fed and result round trips overlap both compute and each other. On
  remote-attached TPUs (PJRT proxy/tunnel) a fetch round trip costs
  O(100ms); serializing one per dispatch was the round-3 serving
  bottleneck. Host np mirrors (updated at drain) drive admission and are
  re-uploaded only after host-side events (abort, overflow stop); a full
  drain (``keep=0``) barriers on the fetcher first, so re-uploads never
  rewind slots past results still in flight.

Weight hot-swap = atomic ``self.params`` swap between steps (buffer shapes
and shardings unchanged → no recompilation), mirroring the reference's
update_weights_from_tensor contract. ``release_memory`` frees the KV pool
when idle — the TPU analogue of SGLang's release_memory_occupation for
colocated time-slicing.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import os
import queue
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from polyrl_tpu import obs
from polyrl_tpu.models import decoder
from polyrl_tpu.obs.engine_profile import EngineLoopProfiler
from polyrl_tpu.rollout.engine import next_bucket
from polyrl_tpu.rollout.flightdeck import EngineFlightDeck, ThroughputEWMA
from polyrl_tpu.rollout.kvledger import PageLedger
from polyrl_tpu.rollout.kvspill import HostSpillPool
from polyrl_tpu.rollout.prefix_cache import PrefixCache
from polyrl_tpu.rollout.sampling import (
    SamplingParams,
    sample_token_vec,
    spec_verify_sample_vec,
)

log = logging.getLogger(__name__)

STREAM_END = object()  # terminal marker on every request's output queue

MAX_STOP_TOKENS = 8

# reusable no-op phase context (contextlib.nullcontext is reentrant):
# _phase() hands this out when the loop profiler is off so the hot path
# pays one attribute read, not an allocation
_NULL_PHASE = contextlib.nullcontext()


def device_ngram_propose(tok_buf: jnp.ndarray, hist_len: jnp.ndarray,
                         n_draft: int) -> jnp.ndarray:
    """Vectorized prompt-lookup proposal on device: for each slot, find the
    LATEST earlier occurrence of the history's final TRIGRAM in
    ``tok_buf[s, :hist_len[s]]`` — falling back to the final bigram, then
    to repeating the last token — and propose the ``n_draft`` tokens that
    followed the match. Longer context matches are what make prompt-lookup
    precise on repetitive text (a repeated bigram often continues
    differently; a repeated trigram rarely does). Rejection sampling keeps
    ANY proposal distribution-exact — a bad guess only wastes verify
    FLOPs. O(S·L) compares; jit-safe static shapes.

    tok_buf: [S, L] int32 (prompt + generated, front-filled)
    hist_len: [S] int32 valid-prefix lengths
    returns: [S, n_draft] int32
    """
    s, length = tok_buf.shape
    rows = jnp.arange(s)
    t_last = tok_buf[rows, jnp.clip(hist_len - 1, 0, length - 1)]
    t_prev = tok_buf[rows, jnp.clip(hist_len - 2, 0, length - 1)]
    t_prev2 = tok_buf[rows, jnp.clip(hist_len - 3, 0, length - 1)]
    idx2 = jnp.arange(length - 1)
    # bigram match at p: buf[p] == t_prev and buf[p+1] == t_last, with the
    # matched bigram strictly before the final one (p+1 < hist_len-1)
    m2 = ((tok_buf[:, :-1] == t_prev[:, None])
          & (tok_buf[:, 1:] == t_last[:, None])
          & (idx2[None] + 1 < (hist_len - 1)[:, None]))
    p2 = jnp.max(jnp.where(m2, idx2[None], -1), axis=1)           # latest
    found2 = (p2 >= 0) & (hist_len >= 3)
    # trigram match at p: buf[p:p+3] == (t_prev2, t_prev, t_last), matched
    # strictly before the final trigram (p+2 < hist_len-1)
    idx3 = jnp.arange(length - 2)
    m3 = ((tok_buf[:, :-2] == t_prev2[:, None])
          & (tok_buf[:, 1:-1] == t_prev[:, None])
          & (tok_buf[:, 2:] == t_last[:, None])
          & (idx3[None] + 2 < (hist_len - 1)[:, None]))
    p3 = jnp.max(jnp.where(m3, idx3[None], -1), axis=1)
    found3 = (p3 >= 0) & (hist_len >= 4)
    # continuation starts right after whichever match won
    start = jnp.where(found3, p3 + 3, p2 + 2)
    found = found3 | found2
    gather = jnp.clip(start[:, None] + jnp.arange(n_draft)[None], 0,
                      length - 1)
    cont = jnp.take_along_axis(tok_buf, gather, axis=1)
    # past-the-history continuation positions fall back to the last token
    cont = jnp.where(gather < hist_len[:, None], cont, t_last[:, None])
    return jnp.where(found[:, None], cont,
                     jnp.broadcast_to(t_last[:, None], (s, n_draft))
                     ).astype(jnp.int32)


@dataclasses.dataclass
class _Request:
    rid: str
    input_ids: list[int]
    sampling: SamplingParams
    out: queue.Queue
    abort: Any  # threading.Event-like or None
    t_submit: float = 0.0  # admission timestamp (per-request latency obs)
    # group-shared prefill hint (GRPO: rollout_n completions of one prompt
    # submitted together): members of a group share group_id; group_size is
    # the expected member count. The engine prefills the shared prompt ONCE
    # and batch-attaches the siblings to the published pages — the hint
    # sizes the pre-taken prefix refs; the attach batching itself is
    # structural (prompt-equality through the prefix cache), so a missing
    # or wrong hint degrades to per-request admission, never corrupts.
    group_id: str = ""
    group_size: int = 0


@dataclasses.dataclass
class _SlotInfo:
    req: _Request
    pages: list[int]            # slot-PRIVATE pages (freed on finalize)
    stop_set: set
    cache_entries: list = dataclasses.field(default_factory=list)
    # prefix-cache refs (released on finalize; cache owns those pages)
    # tokens already streamed to the client (partial-rollout salvage: the
    # abort path publishes prompt+emitted pages so a continuation landing
    # back on this engine re-uses the decoded KV) + the weight version the
    # slot was admitted under (KV written across a swap must not be
    # published — the cache flush on update_weights would be defeated)
    emitted: list = dataclasses.field(default_factory=list)
    admit_version: int = 0


class PageAllocator:
    """Free-list allocator over pages 1..n-1 (page 0 = reserved null page)."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free = list(range(num_pages - 1, 0, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        if n > len(self._free):
            return None
        out = self._free[-n:]
        del self._free[-n:]
        return out

    def free(self, pages: list[int]) -> None:
        self._free.extend(pages)


class CBEngine:
    """Continuous-batching engine; drop-in serving backend for RolloutServer."""

    def __init__(
        self,
        cfg: decoder.ModelConfig,
        params: Any,
        max_slots: int = 64,
        page_size: int = 64,
        num_pages: int | None = None,
        max_seq_len: int = 8192,
        prompt_buckets: tuple[int, ...] = (128, 256, 512, 1024, 2048, 4096),
        kv_cache_dtype=jnp.bfloat16,
        pad_token_id: int = 0,
        seed: int = 0,
        enable_prefix_cache: bool = True,
        steps_per_dispatch: int = 8,
        pipeline_depth: int | None = None,
        mesh=None,
        prefill_chunk: int = 0,
        trace: bool | None = None,
        spec_tokens: int = 0,
        spec_rounds: int = 2,
        salvage_partials: bool = True,
        admit_wave: int | None = None,
        admit_reorder_window: int = 8,
        group_share: bool = True,
        decode_group_share: bool = True,
        group_preref_ttl_s: float | None = None,
        kv_ledger: bool = True,
        kv_cold_after_dispatches: int = 256,
        kv_spill: bool = True,
        kv_spill_host_gb: float = 4.0,
        kv_spill_high_watermark: float = 0.92,
        kv_spill_low_watermark: float = 0.80,
        loop_profile: bool = True,
    ):
        if any(b % page_size for b in prompt_buckets):
            raise ValueError("prompt buckets must be page-aligned")
        if prefill_chunk < 0 or prefill_chunk % page_size:
            # assert would be skipped under -O, and -8 % 8 == 0 would let a
            # negative (still truthy) chunk size enable chunking
            raise ValueError(
                f"prefill_chunk must be a non-negative multiple of "
                f"page_size={page_size}, got {prefill_chunk}")
        self.cfg = cfg
        self.mesh = mesh
        if mesh is not None:
            # tensor-parallel serving (the reference's SGLang --tp-size
            # role, launch_sglang.sh:13): params shard over (fsdp, tp) per
            # decoder.param_specs, KV pools over tp on the head dim, and
            # GSPMD inserts the attention/matmul collectives inside the
            # existing compiled step — no engine-logic changes. Quantized
            # trees shard via quant_param_specs.
            tp = mesh.shape.get("tp", 1)
            if cfg.num_heads % tp or cfg.num_kv_heads % tp:
                raise ValueError(
                    f"tp={tp} must divide num_heads ({cfg.num_heads}) and "
                    f"num_kv_heads ({cfg.num_kv_heads}) — the KV pools and "
                    "paged attention shard on the head dim")
            params = self._shard_params_for_mesh(params)
        self.params = params
        self.max_slots = max_slots
        self.page_size = page_size
        self.max_seq_len = max_seq_len
        self.pages_per_slot = -(-max_seq_len // page_size)
        # default pool: enough for half the slots at full length + slack
        self.num_pages = num_pages or (max_slots * self.pages_per_slot // 2 + 1)
        self.prompt_buckets = prompt_buckets
        self.kv_cache_dtype = kv_cache_dtype
        self.pad_token_id = pad_token_id

        s, p = max_slots, self.pages_per_slot
        self._page_table = np.zeros((s, p), np.int32)
        self._seq_lens = np.zeros((s,), np.int32)
        self._last_tokens = np.full((s,), pad_token_id, np.int32)
        self._n_generated = np.zeros((s,), np.int32)
        self._budgets = np.zeros((s,), np.int32)
        self._active = np.zeros((s,), bool)
        self._temps = np.ones((s,), np.float32)
        self._top_ps = np.ones((s,), np.float32)
        self._top_ks = np.zeros((s,), np.int32)
        self._stop_table = np.full((s, MAX_STOP_TOKENS), -1, np.int32)
        self._slots: list[_SlotInfo | None] = [None] * s
        # per-slot admission generation: queued emit entries record the
        # generation they were dispatched against, so an entry that outlives
        # its slot (finalized via the device-done path, then reused by a new
        # admission before the entry drains) is detected and skipped instead
        # of leaking pad tokens into the new request's stream (ABA race)
        self._slot_gen = np.zeros((s,), np.int64)

        self.allocator = PageAllocator(self.num_pages)
        # KV memory plane (rollout/kvledger.py): per-page owner/role/age
        # ledger + hot/warm/cold residency tiers, fed synchronously at
        # every page transition below. None (rollout.kv_ledger=false)
        # disables all accounting — the engine's output is bitwise
        # identical either way (the ledger never touches RNG, device state
        # or scheduling).
        self.kvledger = (PageLedger(
            self.num_pages, page_size,
            cold_after_dispatches=kv_cold_after_dispatches)
            if kv_ledger else None)
        self._weight_bytes: int | None = None  # cached tree-leaves total
        # the cache frees through _free_cache_pages so the ledger sees the
        # cause the cache booked (capacity / flush / preref_ttl)
        self.prefix_cache = (PrefixCache(page_size, self._free_cache_pages)
                             if enable_prefix_cache else None)
        # host-RAM KV spill tier (rollout/kvspill.py): cold published
        # prefix-cache pages page out to host under watermark pressure and
        # restore on a prefix hit. Requires the ledger (candidate ranking
        # + accounting) and the prefix cache (the spillable population) —
        # kv_ledger=False therefore disables the sweep entirely, keeping
        # the off-engine bitwise identical (spill never touches RNG or
        # device state unless a spill/restore actually fires, and with the
        # pool absent none can).
        self.kvspill = (HostSpillPool(
            capacity_bytes=int(float(kv_spill_host_gb) * 1e9))
            if (kv_spill and kv_ledger and enable_prefix_cache) else None)
        if not 0.0 < kv_spill_low_watermark <= kv_spill_high_watermark <= 1.0:
            raise ValueError(
                f"kv spill watermarks must satisfy 0 < low <= high <= 1, "
                f"got low={kv_spill_low_watermark} "
                f"high={kv_spill_high_watermark}")
        self.kv_spill_high_watermark = float(kv_spill_high_watermark)
        self.kv_spill_low_watermark = float(kv_spill_low_watermark)
        if self.prefix_cache is not None and self.kvledger is not None:
            # cold-first capacity eviction (ledger idle age beats
            # insertion order) — on whenever the ledger is, spill or not
            self.prefix_cache.idle_age = self.kvledger.idle_age
        if self.kvspill is not None:
            self.prefix_cache.drop_spilled = self._drop_spilled_entries
        self._pools = self._make_pools()
        self._rng = jax.random.PRNGKey(seed)

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._pending: collections.deque = collections.deque()
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        # serializes pool use (admit/step) against release_memory freeing it
        self._pool_lock = threading.Lock()
        self._loop_thread: threading.Thread | None = None

        self._step_fns: dict = {}
        self._prefill_fns: dict = {}
        # device-resident control state (mirrors of the np arrays above) and
        # the deferred-emission pipeline: dispatches (prefills + steps) are
        # queued async and their (token, logp, done) outputs fetched later,
        # so device compute overlaps the tunnel round trips and streaming
        self._dev_state: dict | None = None
        # fetch pipeline (loop thread dispatches; fetcher thread transfers):
        #   _emit_q     dispatched outputs awaiting device_get
        #   _fetched_q  (epoch, entry, np arrays) awaiting emission
        #   _fetch_inflight  entries inside the fetcher's current device_get
        #   _fetch_epoch     bumped by _recover/stop: stale results dropped
        # all four guarded by _fetch_cv; emission stays on the loop thread
        self._emit_q: collections.deque = collections.deque()
        self._fetched_q: collections.deque = collections.deque()
        self._fetch_cv = threading.Condition()
        self._fetch_inflight = 0
        self._fetch_epoch = 0
        self._fetch_exc: BaseException | None = None
        self._fetch_thread: threading.Thread | None = None
        # per-slot lower bound on tokens the in-flight dispatches will
        # deliver (loop thread only) — drives the tail cutoff in
        # _step_once: once every mirror-active slot's remaining budget is
        # covered by work already in flight FOR THAT SLOT, dispatching
        # more could only produce pad rows. Per-slot matters: a slot
        # admitted after a dispatch launched gets nothing from it.
        self._inflight_tok = np.zeros(s, np.int64)
        # in-flight dispatch budget: how far the loop runs ahead of emission.
        # Needs ~2*ceil(fetch RTT / per-dispatch compute): the fetcher pulls
        # the oldest half-window per round trip while the newer half
        # computes, so 16 hides a ~300 ms tunnel RTT at ~40 ms/dispatch.
        # Cost: up to this many run-ahead dispatches after the last slot
        # finishes (near-free on device: the step no-ops via lax.cond when
        # nothing is active) and that much abort/admission latency.
        # 0 = fully synchronous (drain every dispatch); negative would make
        # the drain's `outstanding <= keep` exit unreachable and spin the
        # loop thread forever
        if pipeline_depth is None:
            pipeline_depth = int(os.environ.get("POLYRL_CB_PIPELINE") or 16)
        self.pipeline_depth = max(0, int(pipeline_depth))
        # fused decode steps per dispatch (multi-step scheduling): divides
        # dispatch/fetch overhead by k at the cost of ≤(k-1) wasted
        # device iterations per finished slot and up to k steps of
        # abort/admission latency
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        # chunked prefill (the vLLM/SGLang feature, static-shape style):
        # prompts longer than this prefill one chunk per loop iteration,
        # interleaved with decode steps, so a 4k-token admission cannot
        # stall every running stream for a whole long prefill dispatch.
        # 0 disables (prompts prefill in one dispatch as before).
        self.prefill_chunk = int(prefill_chunk)
        self._chunk_jobs: collections.deque = collections.deque()
        # prompt-lookup speculative decoding (opt-in): each decode dispatch
        # runs spec_rounds fused speculation rounds; every round proposes
        # spec_tokens draft tokens per slot by DEVICE-side n-gram lookup
        # (trigram-preferred, bigram fallback) in a device token buffer,
        # verifies them all in ONE forward, and
        # distribution-exact rejection sampling (spec_verify_sample_vec)
        # emits the accepted prefix + 1 — up to spec_tokens+1 tokens per
        # weight read instead of 1. Fully device-resident (proposals, the
        # token history, acceptance) so spec dispatches pipeline like
        # normal steps — no host round trip per round. Wins when outputs
        # are locally repetitive (math/code CoT); costs m× attention reads
        # per verify, so it trades against very long contexts.
        if spec_tokens < 0:
            raise ValueError(f"spec_tokens must be >= 0, got {spec_tokens}")
        if spec_rounds < 1:
            raise ValueError(f"spec_rounds must be >= 1, got {spec_rounds}")
        self.spec_tokens = int(spec_tokens)
        self.spec_rounds = int(spec_rounds)
        # per-slot token history mirror (prompt + emitted) — rebuilds the
        # device token buffer on state re-uploads; spec mode only
        self._hist: list[list[int] | None] | None = (
            [None] * s if self.spec_tokens > 0 else None)
        self.spec_emitted = 0     # tokens emitted by spec dispatches
        self.spec_dispatches = 0  # spec dispatch count (acceptance telemetry)
        self.chunk_dispatches = 0  # chunked-prefill extend dispatch count

        # admission scheduler geometry (ARCHITECTURE.md "Group-shared
        # prefill"). admit_wave: max admissions fused into one batched
        # prefill dispatch. admit_reorder_window: how many blocked heads
        # _collect_wave may SKIP past while forming a wave (a sibling
        # waiting for its leader's publish, a prefix hit amid a fresh wave,
        # a chunk-bound prompt) so one waiting request never freezes
        # admission of everything queued behind it; 0 restores strict FIFO
        # head-of-line. group_share: prefill a shared prompt once and
        # batch-attach its siblings to the published pages (False restores
        # per-request singleton suffix admission — the bench A/B baseline).
        self.admit_wave = max(1, int(admit_wave if admit_wave is not None
                                     else self.ADMIT_WAVE))
        self.admit_reorder_window = max(0, int(admit_reorder_window))
        self.group_share = bool(group_share)
        # admission counters (server_info / bench): dispatches, not
        # requests — the dispatch count is what bounds admission throughput
        self.prefill_dispatches = 0         # all admission dispatches
        self.sibling_attach_dispatches = 0  # batched suffix-attach dispatches
        self.group_forked_requests = 0      # requests admitted by attach wave
        # group pre-ref registry: leader publish pre-takes group_size-1 refs
        # on the shared prefix entries so pool-pressure eviction can't race
        # the siblings' attach; consumed per attach, TTL-swept for groups
        # whose siblings never arrive, disbanded on any cache flush.
        # Guarded by _pool_lock (same discipline as the prefix cache).
        self._group_prerefs: dict[str, dict] = {}
        # sibling-wait pre-ref expiry (config rollout.group_preref_ttl_s;
        # the class attr stays as the compatibility default)
        self.group_preref_ttl_s = float(
            group_preref_ttl_s if group_preref_ttl_s is not None
            else self.GROUP_PREREF_TTL_S)

        # shared-prefix decode attention (ARCHITECTURE.md "Shared-prefix
        # decode attention"): decode group table — group_id → the group's
        # shared prefix page chain + the live member slots. Decode
        # dispatches with >=2 live members per group route through the
        # two-phase grouped paged-attention kernel (ONE HBM stream of the
        # prompt KV per group instead of one per sibling); singleton
        # leftovers and decode_group_share=False degrade to the ungrouped
        # kernel (bitwise the pre-PR decode path). Loop-thread only.
        self.decode_group_share = bool(decode_group_share)
        self._decode_groups: dict[str, dict] = {}
        self._slot_decode_gid: dict[int, str] = {}
        self._grouped_attn = None  # built lazily (TP wrapper under a mesh)
        self.grouped_decode_dispatches = 0  # dispatches that ran grouped

        # token-level continuous generation (partial-rollout salvage): on
        # abort/preempt/shutdown the run-ahead pipeline is DRAINED into the
        # stream instead of dropped, the terminal line is a partial the
        # manager/trainer resume from, and the decoded pages are published
        # to the prefix cache so a continuation landing back here re-uses
        # the KV. False restores fastest-abort semantics (drop in-flight).
        self.salvage_partials = bool(salvage_partials)
        self.tokens_salvaged = 0   # tokens flushed into abort partials
        self.salvage_published_pages = 0  # decoded pages kept via the cache

        # serving telemetry (server_info contract). last_gen_throughput is
        # EWMA-smoothed (flightdeck.ThroughputEWMA): heartbeat-sampled
        # consumers (manager stats poller, /statusz) must not alias on one
        # fast/slow drain tick.
        self.weight_version = 0
        self.num_running = 0
        self.num_queued = 0
        self.last_gen_throughput = 0.0
        self.total_tokens_served = 0
        self._tok_window: collections.deque = collections.deque(maxlen=64)
        self._tput_ewma = ThroughputEWMA()
        # engine flight deck: per-request lifecycle (queue wait / TTFT /
        # TPOT / token counts) + scheduler occupancy ledger, with exact
        # request-vs-scheduler token reconciliation (flightdeck.py)
        self.deck = EngineFlightDeck(max_slots, self.num_pages, page_size)
        # speculative acceptance ceiling: tokens the spec dispatches COULD
        # have emitted (active_slots * rounds * (spec_tokens+1) each) —
        # spec_emitted / this ratio is the acceptance-rate gauge
        self.spec_token_ceiling = 0
        # POLYRL_CB_TRACE=1: cumulative wall per engine phase (dispatch vs
        # fetch vs prefill vs host bookkeeping) — the serving-path analogue
        # of the trainer's marked_timer spans (SURVEY.md §5.1)
        if trace is None:  # explicit arg wins; env is the ops-facing toggle
            trace = bool(os.environ.get("POLYRL_CB_TRACE"))
        self._trace_enabled = bool(trace)
        # engine-loop profiler (obs/engine_profile.py): exhaustive phase
        # attribution of every loop iteration, the windowed device-vs-host
        # split, and the accounting-overhead gauge. When on it ABSORBS the
        # legacy trace seam (one accounting path: _tmark feeds the
        # profiler's legacy counters). rollout.loop_profile=False restores
        # the pre-profiler loop bit for bit — the profiler never touches
        # RNG, device state or scheduling, only clocks around them.
        self.profiler = EngineLoopProfiler() if loop_profile else None
        self._trace: dict | None = (
            collections.defaultdict(float)
            if trace and self.profiler is None else None)
        # the fetcher thread marks "fetch"; += on a shared dict is a
        # non-atomic read-modify-write against the loop thread's marks
        self._trace_lock = threading.Lock()

    def trace_report(self) -> dict:
        """Cumulative seconds per phase (POLYRL_CB_TRACE=1), else empty."""
        if self.profiler is not None:
            return self.profiler.legacy_report() if self._trace_enabled \
                else {}
        return dict(self._trace or {})

    def _phase(self, name: str):
        """Profiler phase context for ``name`` (no-op when off)."""
        prof = self.profiler
        return prof.phase(name) if prof is not None else _NULL_PHASE

    def loop_profile_info(self) -> dict:
        """Flat server_info fields for the loop profiler ({} when off).
        Safe from HTTP handler threads: the profiler locks internally."""
        if self.profiler is None:
            return {}
        return self.profiler.server_info_fields()

    def loop_profile_snapshot(self) -> dict:
        """The /statusz ``engine.loop`` block (always present: a disabled
        profiler reports ``{"enabled": False}`` so one curl answers
        whether the plane is on)."""
        if self.profiler is None:
            return {"enabled": False}
        return self.profiler.snapshot()

    # -- KV memory plane (rollout/kvledger.py) -------------------------------

    # cache-side free causes → ledger taxonomy
    _CACHE_CAUSE = {"capacity": "cache_pressure", "flush": "flush",
                    "preref_ttl": "preref_ttl"}

    def _free_cache_pages(self, pages: list[int]) -> None:
        """The prefix cache's free callback: return the pages to the
        allocator, then attribute them in the ledger with the cause the
        cache booked just before calling (PrefixCache._free)."""
        self.allocator.free(pages)
        if self.kvledger is not None:
            cause = getattr(self.prefix_cache, "last_free_cause", "capacity")
            self.kvledger.on_free(pages,
                                  self._CACHE_CAUSE.get(cause,
                                                        "cache_pressure"))

    def _accounted_bytes(self) -> float:
        """Bytes the ledger can attribute on device: KV pools + weights
        (weights cached — the tree never changes size across swaps)."""
        if self._weight_bytes is None:
            self._weight_bytes = sum(
                int(x.nbytes) for x in jax.tree_util.tree_leaves(self.params)
                if hasattr(x, "nbytes"))
        pool_b = 0
        pools = self._pools
        if pools is not None:
            pool_b = sum(int(x.nbytes)
                         for x in jax.tree_util.tree_leaves(pools)
                         if hasattr(x, "nbytes"))
        if self.kvledger is not None and pool_b:
            self.kvledger.page_bytes = pool_b // max(1, self.num_pages)
        return float(self._weight_bytes + pool_b)

    def _cache_pages(self) -> int:
        return (self.prefix_cache.num_entries
                if self.prefix_cache is not None else 0)

    def kv_memory_info(self) -> dict:
        """Flat server_info fields for the memory plane ({} when the
        ledger is off). Safe from HTTP handler threads: the ledger locks
        internally and the pool reads are atomic snapshots."""
        if self.kvledger is None:
            return {}
        return self.kvledger.server_info_fields(
            self.allocator.free_count, self._cache_pages(),
            self._accounted_bytes())

    def kv_memory_snapshot(self) -> dict:
        """The /statusz ``memory`` section ({} when the ledger is off).
        The ledger owns the spill page/byte counters; the host-pool truth
        (residency, capacity, copy-lane depth) merges in as
        ``spill.host``."""
        if self.kvledger is None:
            return {}
        snap = self.kvledger.snapshot(
            self.allocator.free_count, self._cache_pages(),
            self._accounted_bytes())
        if self.kvspill is not None:
            snap.setdefault("spill", {})["host"] = self.kvspill.stats()
        return snap

    # -- host-RAM KV spill tier (rollout/kvspill.py) -------------------------

    def _drop_spilled_entries(self, entries: list) -> None:
        """Spilled content died without a restore (cache flush, stale-
        squatter replacement, engine stop): free the host tier and settle
        the ledger — the physical pages were freed at spill time."""
        handles = [e.spill_handle for e in entries if e.spilled]
        for e in entries:
            e.spilled = False
            e.spill_handle = -1
        if not handles:
            return
        self.kvspill.drop(handles)
        if self.kvledger is not None:
            self.kvledger.on_spill_drop(len(handles))

    def _spill_sweep(self) -> None:
        """Per-dispatch watermark check (loop thread, off the traced hot
        path — the same seam as the ledger's residency sweep): page util
        at or over the HIGH watermark spills cold unreferenced published
        pages down toward the LOW watermark. The high/low gap is the
        hysteresis band — demand restores land util between the marks
        without immediately re-arming the sweep, so spill/restore cannot
        thrash page-by-page at a single threshold."""
        n = max(1, self.num_pages - 1)
        util = 1.0 - self.allocator.free_count / n
        if util < self.kv_spill_high_watermark:
            return
        target = int(np.ceil((util - self.kv_spill_low_watermark) * n))
        if target > 0:
            self._spill_pages(target, cold_only=True)

    def _spill_pages(self, target: int, cold_only: bool) -> int:
        """Page out up to ``target`` unreferenced published prefix-cache
        pages, coldest first (``cold_only`` restricts to the ledger's cold
        tier — the sweep's proactive mode; allocation pressure relaxes it
        to any unreferenced published page, still coldest-first, because
        spilling preserves the KV that plain eviction would destroy).
        Returns how many pages were spilled.

        The extraction slices are independent device buffers ordered after
        every previously dispatched write by the pools data dependency, so
        the physical pages return to the allocator immediately; nothing
        can rewrite them until a later prefill reallocates them, which the
        same dependency orders after the extraction."""
        if (self.kvspill is None or self.kvledger is None
                or self._pools is None or target <= 0):
            return 0
        if not self.kvspill.lane_free():
            return 0  # copy lane full: double-buffer backpressure
        with self._phase("spill_sweep"):
            return self._spill_pages_inner(target, cold_only)

    def _spill_pages_inner(self, target: int, cold_only: bool) -> int:
        age = self.kvledger.idle_age
        cands = [(age(e.page), e) for e in self.prefix_cache.spill_candidates()]
        if cold_only:
            cands = [c for c in cands if c[0] >= self.kvledger.cold_after]
        if not cands:
            return 0
        cands.sort(key=lambda c: (-c[0], c[1].tick))
        page_bytes = int(self.kvledger.page_bytes)
        if page_bytes <= 0:
            self._accounted_bytes()  # sets ledger.page_bytes from the pools
            page_bytes = int(self.kvledger.page_bytes)
        take = min(target, len(cands))
        while take > 0 and not self.kvspill.can_spill(take, page_bytes):
            take -= 1  # host capacity: spill what fits, never evict here
        if take <= 0:
            return 0
        entries = [e for _age, e in cands[:take]]
        pages = [e.page for e in entries]
        kp, vp = self._pools
        idx = jnp.asarray(np.asarray(pages, np.int32))
        k_dev = jnp.stack([kp[layer][:, idx] for layer in range(len(kp))])
        v_dev = jnp.stack([vp[layer][:, idx] for layer in range(len(vp))])
        handles = self.kvspill.spill(k_dev, v_dev, len(pages), page_bytes)
        for e, h in zip(entries, handles):
            e.spilled = True
            e.spill_handle = h
        self.allocator.free(pages)
        self.kvledger.on_spill(pages)
        return len(pages)

    def _restore_matched(self, matched_entries: list
                         ) -> tuple[list[int], list]:
        """A prefix-cache match landed on spilled entries: restore them
        into fresh physical pages before the attach (restore-then-attach).
        If pages for the full chain cannot be found, the chain truncates
        at the first still-spilled entry (the dropped tail's match refs
        are released) — a shorter hit, never a corrupt one. Returns the
        (possibly truncated) page list + entry list."""
        spilled = [e for e in matched_entries if e.spilled]
        if spilled and not self._restore_entries(spilled):
            cut = next(i for i, e in enumerate(matched_entries) if e.spilled)
            self.prefix_cache.release(matched_entries[cut:])
            matched_entries = matched_entries[:cut]
        return [e.page for e in matched_entries], matched_entries

    def _restore_entries(self, entries: list) -> bool:
        """Batch-restore spilled entries into freshly allocated physical
        pages (host→device, one scatter per layer). The new physical index
        is fine: every consumer goes through the page-table indirection,
        and decode-group seating keys on exact physical chains so a
        restored chain simply decodes solo. Returns False (nothing
        restored) when no pages can be found even after spilling colder
        pages / evicting the cache."""
        with self._phase("restore"):
            return self._restore_entries_inner(entries)

    def _restore_entries_inner(self, entries: list) -> bool:
        need = len(entries)
        pages = self.allocator.alloc(need)
        while pages is None and self._outstanding():
            self._drain_emit_q(keep=self._outstanding() - 1)
            pages = self.allocator.alloc(need)
        if pages is None:
            # colder spillable pages can make room without losing KV;
            # the entries being restored are already spilled, so they are
            # not candidates — no recursion, no self-displacement
            if self._spill_pages(need - self.allocator.free_count,
                                 cold_only=False):
                pages = self.allocator.alloc(need)
        if pages is None and self.prefix_cache.evict(
                need - self.allocator.free_count):
            pages = self.allocator.alloc(need)
        if pages is None:
            return False
        k_host = np.stack([self.kvspill.fetch(e.spill_handle)[0]
                           for e in entries], axis=2)
        v_host = np.stack([self.kvspill.fetch(e.spill_handle)[1]
                           for e in entries], axis=2)
        kp, vp = self._pools
        idx = jnp.asarray(np.asarray(pages, np.int32))
        self._pools = (
            tuple(kp[layer].at[:, idx].set(
                jnp.asarray(k_host[layer]).astype(kp[layer].dtype))
                for layer in range(len(kp))),
            tuple(vp[layer].at[:, idx].set(
                jnp.asarray(v_host[layer]).astype(vp[layer].dtype))
                for layer in range(len(vp))))
        self.kvspill.drop([e.spill_handle for e in entries], restored=True)
        for e, p in zip(entries, pages):
            e.page = int(p)
            e.spilled = False
            e.spill_handle = -1
        if self.kvledger is not None:
            self.kvledger.on_restore(pages)
        return True

    def _shard_params_for_mesh(self, params):
        from polyrl_tpu.models.quant import (
            LoraWeight, QuantWeight, quant_param_specs,
        )
        from polyrl_tpu.parallel import mesh as meshlib

        wrappers = (QuantWeight, LoraWeight)
        leaves = jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, wrappers))
        has_quant = any(
            isinstance(x, QuantWeight)
            or (isinstance(x, LoraWeight) and isinstance(x.base, QuantWeight))
            for x in leaves)
        has_lora = any(isinstance(x, LoraWeight) for x in leaves)
        specs = decoder.param_specs(self.cfg)
        if has_quant:
            specs = quant_param_specs(specs)
        if has_lora:
            # wrapper specs must mirror the wrapper tree or the path-keyed
            # lookup misses every wrapped leaf → silent full replication
            from polyrl_tpu.models.lora import lora_param_specs

            specs = lora_param_specs(specs)
        return meshlib.shard_params(self.mesh, params, specs)

    def _make_pools(self):
        """Paged KV pools; under a mesh, each layer's [Hkv, N, ps, D] pool
        shards its head dim over tp (matching the attention einsums the
        params induce, decoder.cache_specs rationale)."""
        pools = decoder.make_paged_pools(
            self.cfg, self.num_pages, self.page_size,
            dtype=self.kv_cache_dtype)
        if self.mesh is None:
            return pools
        from jax.sharding import NamedSharding, PartitionSpec as P

        from polyrl_tpu.parallel.mesh import TP

        sh = NamedSharding(self.mesh, P(TP, None, None, None))
        return tuple(tuple(jax.device_put(a, sh) for a in side)
                     for side in pools)

    def _tmark(self, key: str, t0: float) -> None:
        if self.profiler is not None:
            # one accounting path: the profiler owns the legacy counters
            if self._trace_enabled:
                self.profiler.mark_legacy(key, time.monotonic() - t0)
        elif self._trace is not None:
            with self._trace_lock:
                self._trace[key] += time.monotonic() - t0
                self._trace["n_" + key] += 1

    # -- compiled pieces ----------------------------------------------------

    def _get_step(self, use_filters: bool, k: int = 1, gshape=None):
        """``k`` fused decode steps per dispatch, state advanced on device.

        The host loop keeps np mirrors for admission decisions but never
        re-uploads state between steps (each host→device array was a tunnel
        round trip — at ~10 uploads + 3 fetches per step the old loop was
        RTT-bound at <100 tok/s on real hardware). Fusing k steps into one
        ``lax.scan`` divides the remaining per-dispatch overhead (enqueue
        RPC + fetch RTT + host bookkeeping) by k as well — the same
        multi-step scheduling vLLM/SGLang use, but expressed as a compiled
        on-device loop. Slots that finish mid-scan go inactive and emit pad
        tokens for the remaining iterations (filtered host-side); inactive
        slots' KV writes are routed to the null page (their freed pages may
        already belong to another request — see forward_paged_decode's
        ``active`` mask). Outputs are [k, slots].

        ``gshape=(ng, gmax, p_pre)`` compiles the shared-prefix GROUPED
        variant: the step takes one extra packed int32 vector carrying the
        dispatch's decode-group tables (seat matrix, shared prefix pages,
        prefix lengths — traced data, so membership churn never retraces)
        and the decode attention routes through the two-phase grouped
        kernel. The shape triple is bucketed by ``_decode_group_pack`` so
        the jit cache stays bounded; ``gshape=None`` is the unchanged
        ungrouped step (bitwise the pre-grouping compiled fn — the
        ``decode_group_share=false`` / singleton degrade path)."""
        key = (use_filters, k, gshape)
        if key not in self._step_fns:
            cfg, pad = self.cfg, self.pad_token_id
            paged_attn = self._tp_paged_attn()
            kv_write = self._tp_kv_write()
            grouped_attn = self._grouped_attn_fn() if gshape else None
            ng, gmax, p_pre = gshape or (0, 0, 0)

            def step(params, kp, vp, rng, page_table, seq_lens, last_tokens,
                     n_generated, budgets, active, temps, top_ps, top_ks,
                     stop_table, group_pack=None):
                if gshape is not None:
                    o = ng * gmax
                    g_slots = group_pack[:o].reshape(ng, gmax)
                    g_pages = group_pack[o:o + ng * p_pre].reshape(ng, p_pre)
                    g_lens = group_pack[o + ng * p_pre:o + ng * p_pre + ng]

                    def attn(q, kp_, vp_, pt, lens):
                        return grouped_attn(q, kp_, vp_, pt, lens, g_slots,
                                            g_pages, g_lens)
                else:
                    attn = paged_attn

                def body(carry, _):
                    kp, vp, rng, seq_lens, last_tokens, n_generated, active = carry
                    logits, (kp, vp) = decoder.forward_paged_decode(
                        params, cfg, last_tokens, seq_lens, (kp, vp),
                        page_table, seq_lens, active=active,
                        attn_fn=attn, kv_write_fn=kv_write)
                    rng, sub = jax.random.split(rng)
                    token, logp = sample_token_vec(
                        logits, sub, temps, top_ps, top_ks,
                        use_filters=use_filters)
                    n_gen = n_generated + active.astype(jnp.int32)
                    hit_stop = jnp.any(token[:, None] == stop_table, axis=-1)
                    done = active & (hit_stop | (n_gen >= budgets))
                    token = jnp.where(active, token, pad)
                    logp = jnp.where(active, logp, 0.0)
                    new_active = active & ~done
                    new_seq = seq_lens + active.astype(jnp.int32)
                    new_last = jnp.where(active, token, last_tokens)
                    return ((kp, vp, rng, new_seq, new_last, n_gen, new_active),
                            (token, logp, done))

                carry, (token, logp, done) = jax.lax.scan(
                    body,
                    (kp, vp, rng, seq_lens, last_tokens, n_generated, active),
                    None, length=k)
                kp, vp, rng, seq_lens, last_tokens, n_generated, active = carry
                return (kp, vp, rng, token, logp, done,
                        seq_lens, last_tokens, n_generated, active)

            self._step_fns[key] = jax.jit(
                step, donate_argnums=(1, 2, 5, 6, 7, 9), static_argnames=())
        return self._step_fns[key]

    def _get_spec_step(self, use_filters: bool, m: int, rounds: int):
        """``rounds`` fused speculation rounds per dispatch, fully
        device-resident. Each round: propose m-1 draft tokens per slot via
        n-gram lookup (trigram preferred) in the device token buffer
        (:func:`device_ngram_propose`), verify all m (the newest real token
        + drafts) in ONE forward, rejection-sample the accepted prefix + 1,
        and write the emitted tokens back into the buffer for the next
        round's lookup. The verify forward IS ``forward_paged_decode`` on
        S·m flattened 'virtual slots' — token (s, i) is a row at position
        seq_lens[s]+i sharing slot s's page table, so the paged-attention
        kernel and KV scatter are reused unchanged; within a layer all m
        rows' KV is scattered before the attention reads, giving exact
        causal semantics. Outputs are [rounds·m, slots] rows + an
        ``emitted`` mask (rejected-draft rows are not real emissions)."""
        key = ("spec", use_filters, m, rounds)
        if key not in self._step_fns:
            cfg, pad = self.cfg, self.pad_token_id
            paged_attn = self._tp_paged_attn()
            kv_write = self._tp_kv_write()
            page_size = self.page_size

            def spec(params, kp, vp, rng, tok_buf, page_table, seq_lens,
                     last_tokens, n_generated, budgets, active, temps,
                     top_ps, top_ks, stop_table):
                s = seq_lens.shape[0]
                buf_len = tok_buf.shape[1]
                rows = jnp.arange(s)
                max_pos = page_table.shape[1] * page_size
                pt_rep = jnp.repeat(page_table, m, axis=0)

                def one_round(carry, _):
                    (kp, vp, rng, tok_buf, seq_lens, last_tokens,
                     n_generated, active) = carry
                    # splice the newest (KV-pending) token into the
                    # history — prefill-sampled first tokens arrive this
                    # way; idempotent for tokens this fn wrote itself
                    tok_buf = tok_buf.at[
                        rows, jnp.clip(seq_lens, 0, buf_len - 1)
                    ].set(last_tokens)
                    draft = device_ngram_propose(tok_buf, seq_lens + 1,
                                                 m - 1)
                    tokens_in = jnp.concatenate(
                        [last_tokens[:, None], draft], 1)
                    pos = (seq_lens[:, None]
                           + jnp.arange(m, dtype=jnp.int32)[None])
                    # rows past the slot's page capacity write to the null
                    # page (garbage logits; budgets stop emission first)
                    okf = (pos < max_pos) & active[:, None]
                    logits, (kp, vp) = decoder.forward_paged_decode(
                        params, cfg, tokens_in.reshape(s * m),
                        pos.reshape(s * m), (kp, vp), pt_rep,
                        pos.reshape(s * m), active=okf.reshape(s * m),
                        attn_fn=paged_attn, kv_write_fn=kv_write)
                    logits = logits.reshape(s, m, -1)
                    rng, sub = jax.random.split(rng)
                    toks, logps, n_acc = spec_verify_sample_vec(
                        logits, draft, sub, temps, top_ps, top_ks,
                        use_filters)
                    # sequential stop/budget semantics over the prefix
                    stopped = jnp.zeros_like(active)
                    n_gen = n_generated
                    emit_cnt = jnp.zeros((s,), jnp.int32)
                    last_emitted = last_tokens
                    out_t, out_l, out_d, out_e = [], [], [], []
                    for i in range(m):  # static unroll, m is small
                        want = active & ~stopped & (i <= n_acc)
                        tok_i = jnp.where(want, toks[:, i], pad)
                        n_gen = n_gen + want.astype(jnp.int32)
                        hit = (jnp.any(tok_i[:, None] == stop_table, axis=-1)
                               & want)
                        done_i = want & (hit | (n_gen >= budgets))
                        out_t.append(tok_i)
                        out_l.append(jnp.where(want, logps[:, i], 0.0))
                        out_d.append(done_i)
                        out_e.append(want)
                        stopped = stopped | done_i
                        emit_cnt = emit_cnt + want.astype(jnp.int32)
                        last_emitted = jnp.where(want, toks[:, i],
                                                 last_emitted)
                    # write emitted tokens into the history at
                    # seq+1 .. seq+emit_cnt (masked rows re-write their
                    # current value — a no-op)
                    emit_mask = jnp.stack(out_e, axis=1)        # [S, m]
                    widx = jnp.clip(pos + 1, 0, buf_len - 1)
                    cur = jnp.take_along_axis(tok_buf, widx, axis=1)
                    tok_buf = tok_buf.at[rows[:, None], widx].set(
                        jnp.where(emit_mask, toks, cur))
                    carry = (kp, vp, rng, tok_buf, seq_lens + emit_cnt,
                             last_emitted, n_gen, active & ~stopped)
                    return carry, (jnp.stack(out_t), jnp.stack(out_l),
                                   jnp.stack(out_d), jnp.stack(out_e))

                carry = (kp, vp, rng, tok_buf, seq_lens, last_tokens,
                         n_generated, active)
                carry, (t, l, d, e) = jax.lax.scan(one_round, carry, None,
                                                   length=rounds)
                (kp, vp, rng, tok_buf, seq_lens, last_tokens, n_generated,
                 active) = carry
                # [rounds, m, S] → [rounds·m, S] rows in emission order
                return (kp, vp, rng, tok_buf,
                        t.reshape(rounds * m, s), l.reshape(rounds * m, s),
                        d.reshape(rounds * m, s), e.reshape(rounds * m, s),
                        seq_lens, last_tokens, n_generated, active)

            self._step_fns[key] = jax.jit(
                spec, donate_argnums=(1, 2, 4, 6, 7, 8, 10))
        return self._step_fns[key]

    def _tp_paged_attn(self):
        """Under a tp>1 mesh the Pallas paged-attention custom call must be
        shard_mapped over the head dim (GSPMD cannot partition custom
        calls); None otherwise → forward_paged_decode's default."""
        if self.mesh is None or self.mesh.shape.get("tp", 1) <= 1:
            return None
        from polyrl_tpu.ops.paged_attention import make_tp_paged_attention

        return make_tp_paged_attention(self.mesh)

    def _grouped_attn_fn(self):
        """The grouped two-phase decode attention callable (built once):
        shard_mapped over the head dim under a tp>1 mesh (same custom-call
        constraint as ``_tp_paged_attn``), the plain dispatcher (Pallas on
        TPU, jnp oracle elsewhere) otherwise. The group tables ride as
        replicated operands either way."""
        if self._grouped_attn is None:
            from polyrl_tpu.ops.paged_attention import (
                grouped_paged_attention,
                make_tp_grouped_paged_attention,
            )

            if self.mesh is not None and self.mesh.shape.get("tp", 1) > 1:
                self._grouped_attn = make_tp_grouped_paged_attention(self.mesh)
            else:
                self._grouped_attn = grouped_paged_attention
        return self._grouped_attn

    def _tp_kv_write(self):
        """Same constraint as _tp_paged_attn for the Pallas K/V write
        kernel; None under no mesh -> forward_paged_decode's default."""
        if self.mesh is None or self.mesh.shape.get("tp", 1) <= 1:
            return None
        from polyrl_tpu.ops.paged_attention import make_tp_paged_kv_write

        return make_tp_paged_kv_write(self.mesh)

    def _insert_slot_state(self, st: dict, slot, prompt_len, token, done,
                           budget, temp, top_p, top_k, stop_row, row):
        """Device-side slot insertion shared by both prefill variants: the
        host never round-trips for admission (a blocking first-token fetch
        flushed the whole pipeline per request — admission-bound serving)."""
        st = dict(st)
        st["seq_lens"] = st["seq_lens"].at[slot].set(prompt_len)
        st["last_tokens"] = st["last_tokens"].at[slot].set(token)
        st["n_generated"] = st["n_generated"].at[slot].set(1)
        st["budgets"] = st["budgets"].at[slot].set(budget)
        st["active"] = st["active"].at[slot].set(~done)
        st["temps"] = st["temps"].at[slot].set(temp)
        st["top_ps"] = st["top_ps"].at[slot].set(top_p)
        st["top_ks"] = st["top_ks"].at[slot].set(top_k)
        st["stop_table"] = st["stop_table"].at[slot].set(stop_row)
        st["page_table"] = st["page_table"].at[slot].set(row)
        return st

    _STATE_KEYS = ("page_table", "seq_lens", "last_tokens", "n_generated",
                   "budgets", "active", "temps", "top_ps", "top_ks",
                   "stop_table")

    # packed-buffer layout for fused prefill uploads: every per-request host
    # value rides ONE int32 vector (floats bitcast) — a dozen separate tiny
    # jnp.asarray uploads per admission dominated the admission cost
    _PACK_SCALARS = 8  # prompt/suffix_len, prefix_len, slot, budget, top_k,
                       # temp_bits, top_p_bits, (pad)

    def _pack_prefill(self, ids, page_ids, row, stops, prefix_ids,
                      len_a, len_b, slot, budget, sp) -> np.ndarray:
        parts = [np.asarray(ids, np.int32), np.asarray(page_ids, np.int32),
                 np.asarray(row, np.int32), np.asarray(stops, np.int32),
                 np.asarray(prefix_ids, np.int32),
                 np.array([len_a, len_b, slot, budget, sp.top_k,
                           np.float32(sp.temperature).view(np.int32),
                           np.float32(sp.top_p).view(np.int32), 0], np.int32)]
        return np.concatenate(parts)

    @staticmethod
    def _unpack_prefill(packed, pb, n_pg, pps, n_pre):
        ids = packed[:pb]; o = pb
        page_ids = packed[o:o + n_pg]; o += n_pg
        row = packed[o:o + pps]; o += pps
        stops = packed[o:o + MAX_STOP_TOKENS]; o += MAX_STOP_TOKENS
        prefix_ids = packed[o:o + n_pre]; o += n_pre
        sc = packed[o:]
        temp = jax.lax.bitcast_convert_type(sc[5], jnp.float32)
        top_p = jax.lax.bitcast_convert_type(sc[6], jnp.float32)
        return (ids, page_ids, row, stops, prefix_ids,
                sc[0], sc[1], sc[2], sc[3], sc[4], temp, top_p)

    def _get_prefill(self, pb: int, use_filters: bool):
        """Fused admission: prefill + sample + insert the slot into the
        device-resident control state, returning (token, logp, done) device
        scalars for DEFERRED emission. ``use_filters`` is a compile-time
        variant: the top-p/top-k sort over the vocab is ~a third of prefill
        wall time and most requests don't need it."""
        key = (pb, use_filters)
        if key not in self._prefill_fns:
            cfg = self.cfg
            n_pg, pps = pb // self.page_size, self.pages_per_slot

            def prefill(params, kp, vp, packed, rng, **state):
                (ids, page_ids, row, stop_row, _pre, prompt_len, _b, slot,
                 budget, top_k, temp, top_p) = self._unpack_prefill(
                    packed, pb, n_pg, pps, 0)
                (kp, vp), last_logits = decoder.prefill_into_pages(
                    params, cfg, ids, prompt_len, (kp, vp), page_ids)
                rng, sub = jax.random.split(rng)
                token, logp = sample_token_vec(
                    last_logits[None], sub, temp[None], top_p[None],
                    top_k[None], use_filters=use_filters)
                token, logp = token[0], logp[0]
                done = jnp.any(token == stop_row) | (budget <= 1)
                st = self._insert_slot_state(
                    state, slot, prompt_len, token, done, budget,
                    temp, top_p, top_k, stop_row, row)
                return kp, vp, rng, token, logp, done, st

            self._prefill_fns[key] = jax.jit(prefill, donate_argnums=(1, 2))
        return self._prefill_fns[key]

    def _get_prefill_batch(self, pb: int, nb: int, use_filters: bool):
        """Fused BATCHED admission: nb requests prefill + sample + insert in
        ONE dispatch (admission dispatch count bounds serving throughput on
        dispatch-latency-bound links — 256 serialized admissions were the
        whole serve wall). ``packed`` is [nb, row]; wave padding rows target
        the dedicated SINK state row (see _ensure_dev_state) so their
        independently sampled tokens can't collide with a real slot."""
        key = ("batch", pb, nb, use_filters)
        if key not in self._prefill_fns:
            cfg = self.cfg
            n_pg, pps = pb // self.page_size, self.pages_per_slot

            def prefill(params, kp, vp, packed, rng, **state):
                o = 0
                ids = packed[:, o:o + pb]; o += pb
                page_ids = packed[:, o:o + n_pg]; o += n_pg
                rows = packed[:, o:o + pps]; o += pps
                stop_rows = packed[:, o:o + MAX_STOP_TOKENS]; o += MAX_STOP_TOKENS
                sc = packed[:, o:]
                prompt_lens, slots = sc[:, 0], sc[:, 2]
                budgets, top_ks = sc[:, 3], sc[:, 4]
                temps = jax.lax.bitcast_convert_type(sc[:, 5], jnp.float32)
                top_ps = jax.lax.bitcast_convert_type(sc[:, 6], jnp.float32)
                (kp, vp), last_logits = decoder.prefill_batch_into_pages(
                    params, cfg, ids, prompt_lens, (kp, vp), page_ids)
                rng, sub = jax.random.split(rng)
                token, logp = sample_token_vec(
                    last_logits, sub, temps, top_ps, top_ks,
                    use_filters=use_filters)
                done = (jnp.any(token[:, None] == stop_rows, axis=-1)
                        | (budgets <= 1))
                st = dict(state)
                st["seq_lens"] = st["seq_lens"].at[slots].set(prompt_lens)
                st["last_tokens"] = st["last_tokens"].at[slots].set(token)
                st["n_generated"] = st["n_generated"].at[slots].set(1)
                st["budgets"] = st["budgets"].at[slots].set(budgets)
                st["active"] = st["active"].at[slots].set(~done)
                st["temps"] = st["temps"].at[slots].set(temps)
                st["top_ps"] = st["top_ps"].at[slots].set(top_ps)
                st["top_ks"] = st["top_ks"].at[slots].set(top_ks)
                st["stop_table"] = st["stop_table"].at[slots].set(stop_rows)
                st["page_table"] = st["page_table"].at[slots].set(rows)
                return kp, vp, rng, token, logp, done, st

            self._prefill_fns[key] = jax.jit(prefill, donate_argnums=(1, 2))
        return self._prefill_fns[key]

    def _get_prefill_extend(self, pb: int, n_prefix_pg: int):
        """Chunked prefill's mid-chunk: fill the chunk's KV attending over
        the already-filled prefix pages — no sampling, no slot insertion
        (the FINAL chunk goes through the suffix path, which samples and
        activates the slot)."""
        key = ("ext", pb, n_prefix_pg)
        if key not in self._prefill_fns:
            cfg = self.cfg
            n_pg, pps = pb // self.page_size, self.pages_per_slot

            def extend(params, kp, vp, packed, rng):
                (ids, page_ids, _row, _stop, prefix_ids, suffix_len,
                 prefix_len, *_rest) = self._unpack_prefill(
                    packed, pb, n_pg, pps, n_prefix_pg)
                (kp, vp), _ = decoder.prefill_suffix_into_pages(
                    params, cfg, ids, suffix_len, prefix_len, (kp, vp),
                    prefix_ids, page_ids)
                return kp, vp, rng

            self._prefill_fns[key] = jax.jit(extend, donate_argnums=(1, 2))
        return self._prefill_fns[key]

    def _pack_suffix(self, tokens, suffix_len: int, prefix_len: int,
                     prefix_pages: list[int], sfx_pages: list[int],
                     row, stops, slot: int, budget: int, sp,
                     pb: int | None = None, n_pre_b: int | None = None):
        """Shared packing for the suffix-attending prefill variants (cache
        hit, chunk extend, chunk final): returns (packed, pb, n_pre_b).
        ``pb``/``n_pre_b`` override the per-request buckets — the batched
        sibling attach packs every wave row to ONE (suffix, prefix-page)
        bucket pair."""
        if pb is None:
            pb = next_bucket(suffix_len, self.prompt_buckets)
        n_sfx_pages = -(-suffix_len // self.page_size)
        page_ids = np.zeros((pb // self.page_size,), np.int32)
        page_ids[:n_sfx_pages] = sfx_pages[:n_sfx_pages]
        if n_pre_b is None:
            n_pre_b = 1
            while n_pre_b < len(prefix_pages):
                n_pre_b *= 2
        prefix_ids = np.zeros((n_pre_b,), np.int32)
        prefix_ids[:len(prefix_pages)] = prefix_pages
        ids = np.full((pb,), self.pad_token_id, np.int32)
        ids[:suffix_len] = tokens
        packed = self._pack_prefill(ids, page_ids, row, stops, prefix_ids,
                                    suffix_len, prefix_len, slot, budget, sp)
        return packed, pb, n_pre_b

    def _advance_chunk_job(self) -> None:
        """One chunk of the head chunked-prefill job — one dispatch per loop
        iteration, so decode steps interleave with long-prompt admission."""
        job = self._chunk_jobs[0]
        req = job["req"]
        if req.abort is not None and req.abort.is_set():
            self._chunk_jobs.popleft()
            self._emit_abort(req)
            self._finalize(job["slot"], cause="abort")
            return
        if self.weight_version != job["version"]:
            # a weight swap landed mid-job: the filled chunks' KV belongs
            # to the OLD weights — finishing (and publishing) would mix
            # weight versions into the freshly flushed prefix cache. Abort;
            # the manager's continuation layer re-dispatches.
            self._chunk_jobs.popleft()
            self._emit_abort(req)
            self._finalize(job["slot"], cause="abort")
            return
        n_prompt = len(req.input_ids)
        remaining = n_prompt - job["pos"]
        if remaining <= self.prefill_chunk:
            # final chunk: standard suffix admission (samples the first
            # token, activates the slot, publishes the whole prompt)
            self._chunk_jobs.popleft()
            self._slots[job["slot"]] = None  # _prefill_request re-creates
            try:
                self._prefill_request(
                    job["slot"], req, job["pages"], job["budget"],
                    matched_pages=job["matched_pages"],
                    matched_entries=job["matched_entries"],
                    own_prefix_pages=job["own_filled"])
            except Exception:
                # mirror _admit's failure contract: the job left the deque
                # and the slot placeholder, so no other path can clean it
                self.allocator.free(job["pages"])
                if self.kvledger is not None:
                    self.kvledger.on_free(job["pages"], "abort")
                if self.prefix_cache is not None:
                    self.prefix_cache.release(job["matched_entries"])
                self._emit_error(req, "prefill failed")
                raise  # pools may be donation-poisoned: _recover resets
            return
        chunk = self.prefill_chunk
        pos = job["pos"]
        prefix_pages = (job["matched_pages"]
                        + job["pages"][:job["own_filled"]])
        n_chunk_pg = chunk // self.page_size
        chunk_pages = job["pages"][job["own_filled"]:
                                   job["own_filled"] + n_chunk_pg]
        packed, pb, n_pre_b = self._pack_suffix(
            req.input_ids[pos:pos + chunk], chunk, pos, prefix_pages,
            chunk_pages, np.zeros((self.pages_per_slot,), np.int32),
            np.full((MAX_STOP_TOKENS,), -1, np.int32), job["slot"], 0,
            req.sampling)
        fn = self._get_prefill_extend(pb, n_pre_b)
        # on failure the job still heads the deque: _recover's
        # _abort_chunk_jobs frees pages/entries and emits the terminal line
        kp, vp, self._rng = fn(self.params, self._pools[0], self._pools[1],
                               jnp.asarray(packed), self._rng)
        self._pools = (kp, vp)
        self.chunk_dispatches += 1
        job["pos"] = pos + chunk
        job["own_filled"] += n_chunk_pg

    def _get_prefill_suffix(self, pb: int, n_prefix_pg: int, use_filters: bool):
        """Prefix-cache-hit fused prefill: compute only the suffix, attend
        over cached prefix pages. Compile key = (suffix bucket, prefix-page
        bucket) — both power-of-two-ish, so the cache stays small."""
        key = ("sfx", pb, n_prefix_pg, use_filters)
        if key not in self._prefill_fns:
            cfg = self.cfg
            n_pg, pps = pb // self.page_size, self.pages_per_slot

            def prefill(params, kp, vp, packed, rng, **state):
                (ids, page_ids, row, stop_row, prefix_page_ids, suffix_len,
                 prefix_len, slot, budget, top_k, temp, top_p) = \
                    self._unpack_prefill(packed, pb, n_pg, pps, n_prefix_pg)
                (kp, vp), last_logits = decoder.prefill_suffix_into_pages(
                    params, cfg, ids, suffix_len, prefix_len, (kp, vp),
                    prefix_page_ids, page_ids)
                rng, sub = jax.random.split(rng)
                token, logp = sample_token_vec(
                    last_logits[None], sub, temp[None], top_p[None],
                    top_k[None], use_filters=use_filters)
                token, logp = token[0], logp[0]
                done = jnp.any(token == stop_row) | (budget <= 1)
                st = self._insert_slot_state(
                    state, slot, prefix_len + suffix_len, token, done, budget,
                    temp, top_p, top_k, stop_row, row)
                return kp, vp, rng, token, logp, done, st

            self._prefill_fns[key] = jax.jit(prefill, donate_argnums=(1, 2))
        return self._prefill_fns[key]

    def _get_prefill_suffix_batch(self, pb: int, nb: int, n_prefix_pg: int,
                                  use_filters: bool):
        """Batched sibling attach: ``nb`` full prefix hits with a UNIFORM
        prefix length prefill their suffixes + sample + insert in ONE
        dispatch (``decoder.prefill_suffix_batch_into_pages``). GRPO's
        G−1 siblings of a published prompt used to admit as G−1 serialized
        singleton suffix dispatches — admission dispatch count linear in
        the rollout count. Wave padding rows target the SINK state row,
        exactly like ``_get_prefill_batch``."""
        key = ("sfxb", pb, nb, n_prefix_pg, use_filters)
        if key not in self._prefill_fns:
            cfg = self.cfg
            n_pg, pps = pb // self.page_size, self.pages_per_slot

            def prefill(params, kp, vp, packed, rng, **state):
                o = 0
                ids = packed[:, o:o + pb]; o += pb
                page_ids = packed[:, o:o + n_pg]; o += n_pg
                rows = packed[:, o:o + pps]; o += pps
                stop_rows = packed[:, o:o + MAX_STOP_TOKENS]; o += MAX_STOP_TOKENS
                prefix_ids = packed[:, o:o + n_prefix_pg]; o += n_prefix_pg
                sc = packed[:, o:]
                suffix_lens, slots = sc[:, 0], sc[:, 2]
                budgets, top_ks = sc[:, 3], sc[:, 4]
                # prefix_len is UNIFORM across the wave (attach contract);
                # row 0 is always a real request (padding is appended)
                prefix_len = sc[0, 1]
                temps = jax.lax.bitcast_convert_type(sc[:, 5], jnp.float32)
                top_ps = jax.lax.bitcast_convert_type(sc[:, 6], jnp.float32)
                (kp, vp), last_logits = decoder.prefill_suffix_batch_into_pages(
                    params, cfg, ids, suffix_lens, prefix_len, (kp, vp),
                    prefix_ids, page_ids)
                rng, sub = jax.random.split(rng)
                token, logp = sample_token_vec(
                    last_logits, sub, temps, top_ps, top_ks,
                    use_filters=use_filters)
                done = (jnp.any(token[:, None] == stop_rows, axis=-1)
                        | (budgets <= 1))
                st = dict(state)
                st["seq_lens"] = st["seq_lens"].at[slots].set(
                    prefix_len + suffix_lens)
                st["last_tokens"] = st["last_tokens"].at[slots].set(token)
                st["n_generated"] = st["n_generated"].at[slots].set(1)
                st["budgets"] = st["budgets"].at[slots].set(budgets)
                st["active"] = st["active"].at[slots].set(~done)
                st["temps"] = st["temps"].at[slots].set(temps)
                st["top_ps"] = st["top_ps"].at[slots].set(top_ps)
                st["top_ks"] = st["top_ks"].at[slots].set(top_ks)
                st["stop_table"] = st["stop_table"].at[slots].set(stop_rows)
                st["page_table"] = st["page_table"].at[slots].set(rows)
                return kp, vp, rng, token, logp, done, st

            self._prefill_fns[key] = jax.jit(prefill, donate_argnums=(1, 2))
        return self._prefill_fns[key]

    def _sink_pad_row(self, pb: int, n_pre: int = 0) -> np.ndarray:
        """A packed prefill row targeting the SINK state row (index
        max_slots): budget 0 → immediately done/inactive, pages all null.
        Used for wave padding and warmup — a duplicated REAL row would
        scatter a conflicting sampled token into the real slot's
        last_tokens/active. ``n_pre`` sizes the (null) prefix-page vector
        for the suffix-prefill variants."""
        pad_sp = SamplingParams(temperature=1.0, top_p=1.0, top_k=0,
                                max_new_tokens=0, stop_token_ids=())
        return self._pack_prefill(
            np.full((pb,), self.pad_token_id, np.int32),
            np.zeros((pb // self.page_size,), np.int32),
            np.zeros((self.pages_per_slot,), np.int32),
            np.full((MAX_STOP_TOKENS,), -1, np.int32),
            np.zeros((n_pre,), np.int32),
            1, 0, self.max_slots, 0, pad_sp)

    def warmup(self, batch_sizes=(2, 4, 8), filter_variants=(False, True),
               suffix: bool = True) -> None:
        """Precompile every admission + decode dispatch variant
        deterministically, before serving traffic.

        Generate-based warmup ("run a few requests first") is unreliable:
        submission trickle and prefix-cache hits fragment admission waves,
        so the larger batch-prefill buckets may never compile during
        warmup — and then a multi-second XLA compile lands inside the
        first real serving burst (observed: ~17 s per bucket for an 8B
        model). This drives each compiled variant once with dummy rows
        targeting the SINK state row (slot index max_slots, null pages) —
        the same mechanism wave padding uses — so pools/state stay valid.
        """
        with self._pool_lock:
            self._ensure_dev_state()
            for pb in self.prompt_buckets:
                base = self._sink_pad_row(pb)
                for uf in filter_variants:
                    self._warm_call(self._get_prefill(pb, uf),
                                    jnp.asarray(base))
                    for nb in batch_sizes:
                        self._warm_call(
                            self._get_prefill_batch(pb, nb, uf),
                            jnp.asarray(np.stack([base] * nb)))
                    if suffix:
                        # prefix-cache-hit variants: power-of-two prefix-
                        # page buckets up to a full prompt's pages — the
                        # second request of a shared-system-prompt workload
                        # hits this path immediately
                        n_pre = 1
                        while n_pre <= max(1, pb // self.page_size):
                            self._warm_call(
                                self._get_prefill_suffix(pb, n_pre, uf),
                                jnp.asarray(self._sink_pad_row(pb, n_pre)))
                            n_pre *= 2
                    if (suffix and self.group_share
                            and pb == self.prompt_buckets[0]):
                        # batched sibling-attach variants: a true attach
                        # wave's suffix is ≤ page_size tokens (full-hit
                        # members), so only the FIRST suffix bucket ever
                        # dispatches — but the prefix-page bucket spans up
                        # to the largest prompt's pages. Warm the full-wave
                        # batch size only (a full GRPO group's siblings);
                        # smaller waves compile on first dispatch.
                        nb_full = max(batch_sizes)
                        n_pre = 1
                        while n_pre <= max(
                                1, self.prompt_buckets[-1] // self.page_size):
                            self._warm_call(
                                self._get_prefill_suffix_batch(
                                    pb, nb_full, n_pre, uf),
                                jnp.asarray(np.stack(
                                    [self._sink_pad_row(pb, n_pre)]
                                    * nb_full)))
                            n_pre *= 2
            for uf in filter_variants:
                st = self._dev_state
                t0 = time.monotonic()
                if self.spec_tokens > 0:
                    # speculative engines route EVERY decode dispatch
                    # through the spec step — precompile it (the k-step
                    # variants would never run)
                    m = self.spec_tokens + 1
                    fn = self._get_spec_step(uf, m, self.spec_rounds)
                    (kp, vp, self._rng, st["tok_buf"], _t, _l, _d, _e,
                     st["seq_lens"], st["last_tokens"], st["n_generated"],
                     st["active"]) = fn(
                        self.params, self._pools[0], self._pools[1],
                        self._rng, st["tok_buf"], st["page_table"],
                        st["seq_lens"], st["last_tokens"],
                        st["n_generated"], st["budgets"], st["active"],
                        st["temps"], st["top_ps"], st["top_ks"],
                        st["stop_table"])
                else:
                    fn = self._get_step(uf, self.steps_per_dispatch)
                    (kp, vp, self._rng, _t, _l, _d, st["seq_lens"],
                     st["last_tokens"], st["n_generated"], st["active"]) = fn(
                        self.params, self._pools[0], self._pools[1],
                        self._rng, st["page_table"], st["seq_lens"],
                        st["last_tokens"], st["n_generated"], st["budgets"],
                        st["active"], st["temps"], st["top_ps"],
                        st["top_ks"], st["stop_table"])
                self._pools = (kp, vp)
                self._tmark("warmup_step", t0)
            jax.block_until_ready(self._pools[0][0])

    def _warm_call(self, fn, packed_dev) -> None:
        """One discarded dispatch of a prefill variant against the sink row
        (pools donated in, updated pools threaded back)."""
        state_kwargs = {k: self._dev_state[k] for k in self._STATE_KEYS}
        t0 = time.monotonic()
        kp, vp, self._rng, _t, _l, _d, new_st = fn(
            self.params, self._pools[0], self._pools[1], packed_dev,
            self._rng, **state_kwargs)
        self._tmark("warmup_prefill", t0)
        self._pools = (kp, vp)
        self._carry_spec_state(new_st, [])
        self._dev_state = new_st

    # -- submission API (server-facing) -------------------------------------

    def submit(self, rid: str, input_ids: list[int], sampling: SamplingParams,
               out: queue.Queue | None = None, abort=None,
               group_id: str = "", group_size: int = 0) -> queue.Queue:
        out = out if out is not None else queue.Queue()
        self._queue.put(_Request(rid, list(input_ids), sampling, out, abort,
                                 time.monotonic(), group_id=str(group_id),
                                 group_size=int(group_size)))
        self.num_queued = self._queue.qsize() + len(self._pending)
        return out

    def start(self) -> "CBEngine":
        if self._loop_thread is None:
            self._loop_thread = threading.Thread(target=self._loop, daemon=True)
            self._loop_thread.start()
        if self._fetch_thread is None:
            self._fetch_thread = threading.Thread(target=self._fetch_loop,
                                                  daemon=True)
            self._fetch_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
        if self._fetch_thread is not None:
            with self._fetch_cv:
                self._fetch_cv.notify_all()
            self._fetch_thread.join(timeout=10.0)
        if self.salvage_partials and self._pools is not None:
            # flush partials instead of dropping them: both engine threads
            # are joined, so the drain's dead-fetcher path lands every
            # dispatched output synchronously and the decoded tokens stream
            # out before the terminal lines below. Best-effort — a poisoned
            # pool must not wedge shutdown.
            try:
                self._drain_emit_q()
            except Exception:  # noqa: BLE001
                log.exception("shutdown salvage drain failed")
        with self._fetch_cv:
            self._fetch_epoch += 1  # orphan anything a hung get still holds
            self._emit_q.clear()
            self._fetched_q.clear()
            self._fetch_exc = None
        self._inflight_tok[:] = 0
        self._invalidate_dev_state()
        # every in-flight and queued request must still see a terminal line +
        # STREAM_END or its HTTP handler thread blocks forever. With salvage
        # on, in-flight requests end in a PARTIAL (abort) — the manager's
        # continuation resumes them elsewhere from the last streamed token —
        # instead of an error that would discard the decoded prefix.
        self._fail_all("engine shutdown",
                       finish_reason="abort" if self.salvage_partials
                       else "error")
        self._decode_groups.clear()
        self._slot_decode_gid.clear()
        if self.prefix_cache is not None:
            # a stopped engine's cached KV (including salvage-published
            # pages) is dead weight: hand every unreferenced page back so
            # page accounting balances after shutdown
            self._disband_group_prerefs()
            self.prefix_cache.flush()
        while self._chunk_jobs:
            job = self._chunk_jobs.popleft()
            self._emit_error(job["req"], "engine shutdown")
            self._finalize(job["slot"], cause="abort")
        self._drain_queue()
        while self._pending:
            self._emit_error(self._pending.popleft(), "engine shutdown")
        if self.kvspill is not None:
            # the cache flush above dropped every spilled entry (both
            # tiers freed); now join the copy lane thread
            self.kvspill.stop()

    # -- weight / memory lifecycle ------------------------------------------

    def update_weights(self, params: Any, version: int | None = None) -> None:
        # atomic ref swap; the loop picks it up on its next step (shapes and
        # shardings identical → the compiled step keeps working). Structure
        # must match exactly: a mismatch (e.g. a bf16 tree swapped into a
        # quantized engine — the caller should re-quantize first, see
        # server.weight_preprocess) would silently retrace every compiled
        # step and double weight HBM; fail loudly instead.
        import jax

        if (jax.tree_util.tree_structure(params)
                != jax.tree_util.tree_structure(self.params)):
            raise ValueError(
                "update_weights tree structure mismatch (quantized engines "
                "need the push re-quantized first — models/quant.py)")
        if self.mesh is not None:
            # keep the compiled step's layout: an in-process push from a
            # colocated trainer arrives host-side/replicated — without the
            # re-shard every weight swap would retrace the decode step (or
            # force the full unsharded tree through one chip's HBM)
            params = self._shard_params_for_mesh(params)
        self.params = params
        self.weight_version = self.weight_version + 1 if version is None else version
        if self.prefix_cache is not None:
            # cached KV belongs to the old weights (the reference flushes the
            # radix cache after every update, patches.py:374-377); group
            # pre-refs ride the entries being flushed — disband them first
            # or the orphans' pages stay pinned until the TTL sweep
            with self._pool_lock:
                self._disband_group_prerefs()
                self.prefix_cache.flush()

    def reset_throughput_window(self) -> None:
        """Zero the rolling tok/s window (serving telemetry). Benchmarks use
        it so one phase's throughput can't leak into the next's peak."""
        self._tok_window.clear()
        self._tput_ewma.reset()
        self.last_gen_throughput = 0.0

    def flush_prefix_cache(self) -> None:
        """Invalidate all cached prefix pages (public surface — weight
        updates do this implicitly; benchmarks/tests use it to isolate
        phases)."""
        with self._pool_lock:
            if self.prefix_cache is not None:
                self._disband_group_prerefs()
                self.prefix_cache.flush()

    def release_memory(self) -> None:
        """Pause serving and, once the decode batch drains, free the KV pool
        (real HBM release for colocated time-slicing — the manager aborts
        in-flight requests first, handlers.rs:500-513)."""
        self._paused.set()
        if self._idle.wait(timeout=30.0):
            with self._pool_lock:
                if not self._active.any():
                    # mid-chunk prefill jobs lose their filled KV with the
                    # pool — abort them (the manager's continuation layer
                    # re-dispatches aborted requests)
                    self._abort_chunk_jobs()
                    if self.prefix_cache is not None:
                        self._disband_group_prerefs()
                        self.prefix_cache.flush()
                    self._pools = None

    def resume_memory(self) -> None:
        with self._pool_lock:
            if self._pools is None:
                self._pools = self._make_pools()
        self._paused.clear()

    # -- engine loop ---------------------------------------------------------

    def _loop(self) -> None:
        prof = self.profiler
        while not self._stop.is_set():
            try:
                if prof is not None:
                    # each iteration is one profiler attribution window:
                    # phase self-times partition its wall, the leftover
                    # lands in the `other` residual (engine_profile.py)
                    with prof.iteration():
                        self._loop_iter()
                else:
                    self._loop_iter()
            except Exception:  # noqa: BLE001 — loop must survive anything:
                # a dead loop wedges every connected HTTP handler forever
                log.exception("engine iteration failed; resetting")
                self._recover()

    def _loop_iter(self) -> None:
        if self._paused.is_set():
            self._drain_emit_q()
            self._idle.set()
            with self._phase("idle"):
                time.sleep(0.02)
            return
        self._drain_queue()
        if (not self._pending and not self._active.any()
                and not self._chunk_jobs):
            self._drain_emit_q()  # drain only ever deactivates slots
            self.deck.on_idle()
            self._idle.set()
            try:
                with self._phase("idle"):
                    req = self._queue.get(timeout=0.05)
                self._pending.append(req)
            except queue.Empty:
                pass
            return
        self._idle.clear()
        with self._pool_lock:
            if self._paused.is_set():  # raced with release_memory
                return
            self._admit()
            if self._chunk_jobs:
                # one chunk per iteration: long-prompt admission interleaves
                # with the decode step below instead of monopolizing the
                # device for the whole prefill
                t0 = time.monotonic()
                with self._phase("prefill_dispatch"):
                    self._advance_chunk_job()
                self._tmark("chunk_prefill", t0)
            if self._active.any():
                self._step_once()
            elif self._pending and not self._chunk_jobs:
                with self._phase("idle"):
                    time.sleep(0.005)  # pending but blocked on pages/slots

    def _abort_chunk_jobs(self) -> None:
        while self._chunk_jobs:
            job = self._chunk_jobs.popleft()
            self._emit_abort(job["req"])
            self._finalize(job["slot"], cause="abort")

    def _recover(self) -> None:
        """After any jit failure the pools may have been donated to the dead
        call; fail everything and reallocate so serving can continue."""
        with self._fetch_cv:
            # bump the epoch FIRST: results a still-running device_get lands
            # after this point are dropped at emission (slot generations
            # would drop most anyway; the epoch also covers mirrors)
            self._fetch_epoch += 1
            self._emit_q.clear()
            self._fetched_q.clear()
            self._fetch_exc = None
        self._inflight_tok[:] = 0
        self._invalidate_dev_state()
        self._fail_all("engine error")
        self._decode_groups.clear()
        self._slot_decode_gid.clear()
        with self._pool_lock:
            self._abort_chunk_jobs()
            if self.prefix_cache is not None:
                self._disband_group_prerefs()
                self.prefix_cache.flush()
            self._pools = self._make_pools()

    def _drain_queue(self) -> None:
        while True:
            try:
                self._pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        self.num_queued = len(self._pending)

    ADMIT_WAVE = 8  # max admissions fused into one batched prefill dispatch
    # sibling-wait pre-ref expiry default; the LIVE value is the
    # ``group_preref_ttl_s`` ctor arg / rollout.group_preref_ttl_s knob
    GROUP_PREREF_TTL_S = 30.0

    def _admit(self) -> None:
        with self._phase("accounting"):
            self._sweep_group_prerefs()
        while self._pending:
            with self._phase("collect_wave"):
                wave, kind = self._collect_wave()
            if not wave:
                break
            try:
                t0 = time.monotonic()
                with self._phase("prefill_dispatch"):
                    if len(wave) == 1:
                        req, slot, pages, budget, mp, me = wave[0]
                        self._prefill_request(slot, req, pages, budget,
                                              mp, me)
                    elif kind == "attach":
                        self._prefill_attach_wave(wave)
                    else:
                        self._prefill_wave(wave)
                self.prefill_dispatches += 1
                self._tmark("prefill_dispatch", t0)
                self.deck.on_admit_wave(len(wave))
            except Exception:
                for req, _slot, pages, _b, _mp, me in wave:
                    self.allocator.free(pages)
                    if self.kvledger is not None:
                        self.kvledger.on_free(pages, "abort")
                    if self.prefix_cache is not None:
                        self.prefix_cache.release(me)
                    self._emit_error(req, "prefill failed")
                raise  # pools may be donation-poisoned: let _recover reset
        self.num_queued = len(self._pending)

    def _collect_wave(self) -> tuple[list, str]:
        """Collect up to ``admit_wave`` admissible requests, reserving a
        slot + pages for each: (req, slot, pages, budget, matched_pages,
        matched_entries), plus the wave kind:

        - ``"fresh"`` — no cached prefix anywhere in the wave: one batched
          full-prompt prefill (or a singleton).
        - ``"attach"`` — every member is a FULL prefix hit with the same
          prefix page count (GRPO siblings of a published prompt, or any
          equal-length full hits): one batched suffix dispatch. Partial
          hits stay singletons — their suffix publishes fresh pages, and
          two same-prompt partials in one dispatch would duplicate that
          publish instead of chaining off it.

        Admission reorder window: a head that cannot join the forming wave
        (a sibling waiting for its leader's publish, a prefix hit amid a
        fresh wave, a chunk-bound prompt) is SKIPPED — left pending while
        scanning continues — up to ``admit_reorder_window`` skips, instead
        of ``break``-ing admission for every unrelated request queued
        behind it. Page exhaustion still ends the scan: skipping past a
        page-starved head would let small requests starve big ones."""
        wave: list = []
        kind = "fresh"
        attach_len = -1  # prefix page count of a forming attach wave
        assigned: set[int] = set()
        wave_page_keys: set = set()
        chunk_keys = {job.get("first_key") for job in self._chunk_jobs}
        chunk_keys.discard(None)
        skipped = 0
        scan = 0
        while len(wave) < self.admit_wave and scan < len(self._pending):
            free = [int(i) for i in np.flatnonzero(
                        ~self._active & np.asarray(
                            [s is None for s in self._slots]))
                    if int(i) not in assigned]
            if not free:
                out = self._outstanding()
                if not wave and out:
                    # finished slots may be hiding behind undrained
                    # outputs: land ONE more fetch batch and re-check —
                    # a full barrier here would stall admission (holding
                    # _pool_lock) for the whole run-ahead pipeline
                    self._drain_emit_q(keep=out - 1)
                    continue
                break
            req = self._pending[scan]
            if req.abort is not None and req.abort.is_set():
                del self._pending[scan]
                self._emit_abort(req)
                self._consume_group_preref(req)  # sibling that never attaches
                continue
            n_prompt = len(req.input_ids)
            if n_prompt == 0 or n_prompt > min(self.max_seq_len - 1,
                                               self.prompt_buckets[-1]):
                del self._pending[scan]
                self._emit_error(req, f"prompt length {n_prompt} unsupported")
                self._consume_group_preref(req)
                continue
            budget = min(req.sampling.max_new_tokens,
                         self.max_seq_len - n_prompt)
            n_pages = -(-(n_prompt + budget) // self.page_size)
            n_full = max(0, (n_prompt - 1) // self.page_size)
            matched_pages: list[int] = []
            matched_entries: list = []
            first_key = None
            if self.prefix_cache is not None:
                matched_pages, matched_entries = self.prefix_cache.match(
                    req.input_ids)
                if self.kvspill is not None and any(
                        e.spilled for e in matched_entries):
                    # a hit on spilled KV restores-then-attaches: the
                    # chain lands in fresh physical pages (truncating at
                    # the first entry that cannot be restored)
                    matched_pages, matched_entries = \
                        self._restore_matched(matched_entries)
                if n_full > 0:
                    first_key = self.prefix_cache._keys_for(
                        req.input_ids, 1)[0]
            full_hit = bool(matched_pages) and len(matched_pages) == n_full
            # sibling wait: the prompt's first full page is being computed
            # by a request already in this wave (GRPO siblings of an
            # unpublished leader) or by an in-flight chunked prefill job —
            # admitting it now would recompute the prefix that is about to
            # be published (structurally defeating the cache)
            sibling_blocked = (not matched_pages and first_key is not None
                              and (first_key in wave_page_keys
                                   or first_key in chunk_keys))
            prefix_cached = len(matched_pages) * self.page_size
            chunked = (self.prefill_chunk
                       and n_prompt - prefix_cached > self.prefill_chunk)
            blocked = sibling_blocked
            if wave:
                if kind == "attach":
                    blocked = blocked or chunked or not (
                        full_hit and len(matched_pages) == attach_len)
                else:
                    blocked = blocked or chunked or bool(matched_pages)
            if blocked:
                if self.prefix_cache is not None:
                    self.prefix_cache.release(matched_entries)
                if skipped >= self.admit_reorder_window:
                    break  # window exhausted: stop reordering, flush wave
                skipped += 1
                scan += 1
                continue
            need = n_pages - len(matched_pages)
            pages = self._try_alloc(need, matched_entries)
            if pages is None:
                break  # pages exhausted: wait (no skip — alloc fairness)
            del self._pending[scan]
            slot = free[0]
            assigned.add(slot)
            if self.kvledger is not None:
                # the single alloc site (every _try_alloc caller lands
                # here): pages become slot-owned active-decode
                self.kvledger.on_alloc(pages,
                                       owner=req.group_id or req.rid)
            if self.prefix_cache is not None:
                self.prefix_cache.note_request(bool(matched_pages))
            if chunked:
                # reserve the slot (placeholder keeps it out of the free
                # scan; active stays False until the final chunk inserts).
                # first_key marks the in-flight prompt so group siblings
                # WAIT for the final chunk's publish instead of
                # re-prefilling the whole prompt in parallel
                self._slots[slot] = _SlotInfo(
                    req, list(pages), set(req.sampling.stop_token_ids),
                    cache_entries=list(matched_entries))
                self._chunk_jobs.append({
                    "req": req, "slot": slot, "pages": list(pages),
                    "matched_pages": list(matched_pages),
                    "matched_entries": list(matched_entries),
                    "budget": budget, "pos": prefix_cached,
                    "own_filled": 0, "version": self.weight_version,
                    "first_key": first_key,
                })
                chunk_keys.add(first_key)
                continue
            if not wave and matched_pages:
                if full_hit and self.group_share:
                    # start an attach wave: later equal-prefix full hits
                    # (the other G-1 siblings) join this dispatch
                    kind, attach_len = "attach", len(matched_pages)
                else:
                    # partial hit (or sharing disabled): singleton suffix
                    wave.append((req, slot, pages, budget, matched_pages,
                                 matched_entries))
                    break
            if not matched_pages and first_key is not None:
                wave_page_keys.add(first_key)
            wave.append((req, slot, pages, budget, matched_pages,
                         matched_entries))
        return wave, kind

    def _try_alloc(self, need: int, matched_entries: list):
        """Page allocation with the drain + cache-evict fallbacks; releases
        the caller's matched cache entries on failure."""
        pages = self.allocator.alloc(need)
        while pages is None and self._outstanding():
            # drain incrementally: finished slots return their pages, and
            # often the oldest fetch batch already holds the finisher
            self._drain_emit_q(keep=self._outstanding() - 1)
            pages = self.allocator.alloc(need)
        if pages is None and self.kvspill is not None:
            # allocation pressure: page unreferenced published KV out to
            # host BEFORE evicting it — spilling preserves what eviction
            # destroys, which is what lets sessions oversubscribe HBM
            if self._spill_pages(need - self.allocator.free_count,
                                 cold_only=False):
                pages = self.allocator.alloc(need)
        if pages is None and self.prefix_cache is not None:
            # pool pressure: evict unreferenced cached pages and retry
            if self.prefix_cache.evict(need - self.allocator.free_count):
                pages = self.allocator.alloc(need)
        if pages is None and self.prefix_cache is not None:
            self.prefix_cache.release(matched_entries)
        return pages

    def _prefill_wave(self, wave: list) -> None:
        """Batched fused admission: ONE dispatch prefills every request in
        the wave (see _get_prefill_batch). The wave is padded to a size
        bucket by repeating row 0 — duplicate scatters write identical
        values and duplicate outputs are never emitted."""
        self._ensure_dev_state()
        state_kwargs = {k: self._dev_state[k] for k in self._STATE_KEYS}
        pb = next_bucket(max(len(r.input_ids) for r, *_ in wave),
                         self.prompt_buckets)
        use_filters = any(r.sampling.top_p < 1.0 or r.sampling.top_k > 0
                          for r, *_ in wave)
        rows_np, metas = [], []
        for req, slot, pages, budget, _mp, _me in wave:
            sp = req.sampling
            n_prompt = len(req.input_ids)
            n_pp = -(-n_prompt // self.page_size)
            page_ids = np.zeros((pb // self.page_size,), np.int32)
            page_ids[:n_pp] = pages[:n_pp]
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:len(pages)] = pages
            stops = np.full((MAX_STOP_TOKENS,), -1, np.int32)
            for i, t in enumerate(sp.stop_token_ids[:MAX_STOP_TOKENS]):
                stops[i] = t
            ids = np.full((pb,), self.pad_token_id, np.int32)
            ids[:n_prompt] = req.input_ids
            rows_np.append(self._pack_prefill(
                ids, page_ids, row, stops, np.zeros((0,), np.int32),
                n_prompt, 0, slot, budget, sp))
            metas.append((req, slot, pages, budget, row, stops))
        nb = next_bucket(len(wave), (2, 4, 8))
        if len(rows_np) < nb:
            pad_row = self._sink_pad_row(pb)
            while len(rows_np) < nb:
                rows_np.append(pad_row)
        fn = self._get_prefill_batch(pb, nb, use_filters)
        kp, vp, self._rng, token, logp, done, new_st = fn(
            self.params, self._pools[0], self._pools[1],
            jnp.asarray(np.stack(rows_np)), self._rng, **state_kwargs)
        self._pools = (kp, vp)
        self._carry_spec_state(new_st,
                               [(slot, req.input_ids)
                                for req, slot, *_rest in metas])
        self._dev_state = new_st

        idxs = []
        for req, slot, pages, budget, row, stops in metas:
            private = list(pages)
            entries: list = []
            if self.prefix_cache is not None:
                published = self.prefix_cache.publish(
                    req.input_ids, pages, n_cached=0)
                pub_pages = {e.page for _, e in published}
                private = [p for p in pages if p not in pub_pages]
                entries = [e for _, e in published]
                if self.kvledger is not None:
                    self.kvledger.on_publish(pub_pages)
            sp = req.sampling
            n_prompt = len(req.input_ids)
            self._page_table[slot] = row
            self._seq_lens[slot] = n_prompt
            self._last_tokens[slot] = self.pad_token_id
            self._n_generated[slot] = 1
            self._budgets[slot] = budget
            self._active[slot] = True
            self._temps[slot] = sp.temperature
            self._top_ps[slot] = sp.top_p
            self._top_ks[slot] = sp.top_k
            self._stop_table[slot] = stops
            self._slots[slot] = _SlotInfo(req, private, set(sp.stop_token_ids),
                                          cache_entries=entries,
                                          admit_version=self.weight_version)
            if self._hist is not None:
                self._hist[slot] = list(req.input_ids)
            self._slot_gen[slot] += 1
            self.deck.on_admit(slot, req.rid, req.t_submit, n_prompt)
            self._consume_group_preref(req)
            self._register_group_prerefs(req, entries)
            # leader seat: its first full prompt pages ARE the chain the
            # siblings will attach to (publish keeps the ids)
            self._register_decode_group(
                req, slot, max(0, (n_prompt - 1) // self.page_size), row)
            idxs.append((slot, int(self._slot_gen[slot])))
        self._enqueue_output(("prefillb", (token, logp, done), idxs,
                              self.weight_version))

    def _prefill_attach_wave(self, wave: list) -> None:
        """Batched sibling attach: every wave member is a FULL prefix hit
        with the SAME prefix page count (GRPO siblings of a published
        leader, or any equal-length full hits) — one
        ``_get_prefill_suffix_batch`` dispatch admits them all, replacing
        G−1 serialized singleton suffix dispatches. Full hits publish
        nothing (the whole prompt's full pages are already cached), so the
        members' suffix/decode pages stay slot-private and their cache
        refs are exactly the ``match()`` entries."""
        self._ensure_dev_state()
        state_kwargs = {k: self._dev_state[k] for k in self._STATE_KEYS}
        attach_pages = len(wave[0][4])
        prefix_len = attach_pages * self.page_size
        pb = next_bucket(max(len(r.input_ids) - prefix_len
                             for r, *_ in wave), self.prompt_buckets)
        n_pre_b = 1
        while n_pre_b < attach_pages:
            n_pre_b *= 2
        use_filters = any(r.sampling.top_p < 1.0 or r.sampling.top_k > 0
                          for r, *_ in wave)
        rows_np, metas = [], []
        for req, slot, pages, budget, mp, me in wave:
            sp = req.sampling
            n_prompt = len(req.input_ids)
            all_pages = mp + pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:len(all_pages)] = all_pages
            stops = np.full((MAX_STOP_TOKENS,), -1, np.int32)
            for i, t in enumerate(sp.stop_token_ids[:MAX_STOP_TOKENS]):
                stops[i] = t
            packed, _pb, _np = self._pack_suffix(
                req.input_ids[prefix_len:], n_prompt - prefix_len,
                prefix_len, mp, pages, row, stops, slot, budget, sp,
                pb=pb, n_pre_b=n_pre_b)
            rows_np.append(packed)
            metas.append((req, slot, pages, budget, row, stops, me))
        nb = next_bucket(len(wave), (2, 4, 8))
        if len(rows_np) < nb:
            pad_row = self._sink_pad_row(pb, n_pre_b)
            while len(rows_np) < nb:
                rows_np.append(pad_row)
        fn = self._get_prefill_suffix_batch(pb, nb, n_pre_b, use_filters)
        kp, vp, self._rng, token, logp, done, new_st = fn(
            self.params, self._pools[0], self._pools[1],
            jnp.asarray(np.stack(rows_np)), self._rng, **state_kwargs)
        self._pools = (kp, vp)
        self._carry_spec_state(new_st,
                               [(slot, req.input_ids)
                                for req, slot, *_rest in metas])
        self._dev_state = new_st

        idxs = []
        for req, slot, pages, budget, row, stops, me in metas:
            sp = req.sampling
            n_prompt = len(req.input_ids)
            self._page_table[slot] = row
            self._seq_lens[slot] = n_prompt
            self._last_tokens[slot] = self.pad_token_id
            self._n_generated[slot] = 1
            self._budgets[slot] = budget
            self._active[slot] = True
            self._temps[slot] = sp.temperature
            self._top_ps[slot] = sp.top_p
            self._top_ks[slot] = sp.top_k
            self._stop_table[slot] = stops
            self._slots[slot] = _SlotInfo(req, list(pages),
                                          set(sp.stop_token_ids),
                                          cache_entries=list(me),
                                          admit_version=self.weight_version)
            if self._hist is not None:
                self._hist[slot] = list(req.input_ids)
            self._slot_gen[slot] += 1
            self.deck.on_admit(slot, req.rid, req.t_submit, n_prompt,
                               cached_tokens=prefix_len)
            self._consume_group_preref(req)
            # sibling seat: the attach wave's matched pages are exactly the
            # leader's published chain (row's leading columns)
            self._register_decode_group(req, slot, attach_pages, row)
            idxs.append((slot, int(self._slot_gen[slot])))
        self.sibling_attach_dispatches += 1
        self.group_forked_requests += len(wave)
        self._enqueue_output(("prefillb", (token, logp, done), idxs,
                              self.weight_version))

    # -- group-shared prefill pre-refs ---------------------------------------

    def _register_group_prerefs(self, req: _Request, entries: list) -> None:
        """After a group leader's prompt pages publish, pre-take
        ``group_size−1`` refs on the chain so pool-pressure eviction can't
        reclaim the shared prefix before the siblings attach. Refs are
        dropped one unit per sibling admission (``_consume_group_preref``),
        TTL-swept for groups whose siblings never arrive, and disbanded
        before any cache flush (the entries are about to be orphaned)."""
        if (not self.group_share or self.prefix_cache is None
                or not req.group_id or req.group_size <= 1 or not entries
                or req.group_id in self._group_prerefs):
            return
        n = req.group_size - 1
        self.prefix_cache.retain(entries, n)
        self._group_prerefs[req.group_id] = {
            "entries": list(entries), "remaining": n,
            "t": time.monotonic(),
        }
        if self.kvledger is not None:
            self.kvledger.on_preref_hold([e.page for e in entries])

    def _consume_group_preref(self, req: _Request) -> None:
        """One group member accounted for (admitted, aborted, or errored
        pre-admission): drop one pre-ref unit on the group's chain."""
        if not req.group_id:
            return
        g = self._group_prerefs.get(req.group_id)
        if g is None:
            return
        if self.prefix_cache is not None:
            self.prefix_cache.release(g["entries"])
        g["remaining"] -= 1
        if g["remaining"] <= 0:
            del self._group_prerefs[req.group_id]
            if self.kvledger is not None:
                self.kvledger.on_preref_release(
                    [e.page for e in g["entries"]])

    def _sweep_group_prerefs(self) -> None:
        """Expire pre-refs for groups whose siblings never arrived (dropped
        groups, mis-sized hints) so the shared pages return to normal LRU
        eviction instead of being pinned forever."""
        if not self._group_prerefs:
            return
        now = time.monotonic()
        for gid in [g for g, v in self._group_prerefs.items()
                    if now - v["t"] > self.group_preref_ttl_s]:
            g = self._group_prerefs.pop(gid)
            if self.prefix_cache is not None:
                for _ in range(max(0, g["remaining"])):
                    # TTL expiry: orphan frees under this release book as
                    # preref_ttl (the page died because the group's
                    # siblings never came for it)
                    self.prefix_cache.release(g["entries"],
                                              cause="preref_ttl")
            if self.kvledger is not None:
                self.kvledger.on_preref_release(
                    [e.page for e in g["entries"]])

    def _disband_group_prerefs(self) -> None:
        """Release every outstanding pre-ref NOW — called before any cache
        flush (weight swap, memory release, recover, shutdown): the flush
        orphans the entries, and pre-refs on orphans would pin their pages
        until the TTL sweep."""
        for g in self._group_prerefs.values():
            if self.prefix_cache is not None:
                for _ in range(max(0, g["remaining"])):
                    self.prefix_cache.release(g["entries"])
            if self.kvledger is not None:
                self.kvledger.on_preref_release(
                    [e.page for e in g["entries"]])
        self._group_prerefs.clear()

    # -- shared-prefix decode groups -----------------------------------------

    def _register_decode_group(self, req: _Request, slot: int,
                               n_pre_pages: int, prefix_pages) -> None:
        """Seat ``slot`` in its GRPO group's decode-sharing table. The seat
        is only taken when the member's leading page-table columns are the
        group's EXACT physical prefix chain (the PR-8 indirection is what
        makes one HBM stream serve everyone) — a member admitted after a
        cache flush re-prefilled onto fresh pages and must not join the
        old cohort (it keeps decoding correctly via the ungrouped path).
        Loop-thread only; membership leaves through ``_finalize``."""
        if (not self.decode_group_share or not self.group_share
                or self.prefix_cache is None or not req.group_id
                or req.group_size <= 1 or n_pre_pages <= 0):
            return
        pages_t = tuple(int(p) for p in list(prefix_pages)[:n_pre_pages])
        if len(pages_t) < n_pre_pages:
            return
        g = self._decode_groups.get(req.group_id)
        if g is None or not g["slots"]:
            g = {"n_pre": int(n_pre_pages), "pages": pages_t, "slots": set()}
            self._decode_groups[req.group_id] = g
        if g["n_pre"] != n_pre_pages or g["pages"] != pages_t:
            return  # different physical prefix (flush mid-group): stay solo
        g["slots"].add(slot)
        self._slot_decode_gid[slot] = req.group_id

    def _drop_decode_seat(self, slot: int) -> None:
        gid = self._slot_decode_gid.pop(slot, None)
        if gid is None:
            return
        g = self._decode_groups.get(gid)
        if g is not None:
            g["slots"].discard(slot)
            if not g["slots"]:
                del self._decode_groups[gid]

    @staticmethod
    def _pow2(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def _decode_group_pack(self):
        """Build this dispatch's decode-group tables from the host registry:
        (packed int32 vector, bucketed (ng, gmax, p_pre) jit key, the
        group rows used — for the KV-read ledger), or (None, None, ())
        when nothing shares. Only groups with >=2 mirror-ACTIVE members
        pack (a lone survivor degrades to the ungrouped kernel — its page
        row still holds the whole sequence); every dimension buckets to a
        power of two so the compiled-step cache stays bounded."""
        if not self.decode_group_share or self.spec_tokens > 0:
            return None, None, ()
        rows = []
        for g in self._decode_groups.values():
            live = sorted(s for s in g["slots"] if self._active[s])
            if len(live) >= 2:
                rows.append((live, g["n_pre"], g["pages"]))
        if not rows:
            return None, None, ()
        ng = self._pow2(len(rows))
        gmax = self._pow2(max(len(r[0]) for r in rows))
        p_pre = self._pow2(max(r[1] for r in rows))
        g_slots = np.full((ng, gmax), -1, np.int32)
        g_pages = np.zeros((ng, p_pre), np.int32)
        g_lens = np.zeros((ng,), np.int32)
        for i, (live, n_pre, pages) in enumerate(rows):
            g_slots[i, :len(live)] = live
            g_pages[i, :n_pre] = pages[:n_pre]
            g_lens[i] = n_pre * self.page_size
        pack = np.concatenate([g_slots.ravel(), g_pages.ravel(), g_lens])
        return pack, (ng, gmax, p_pre), rows

    def _account_kv_reads(self, group_rows, k: int,
                          k_tokens: int | None = None) -> None:
        """Dispatch-time KV-read ledger (host mirrors, no device work):
        LOGICAL pages = what every active slot attends; STREAMED = what the
        kernels actually pull from HBM — each packed group's prefix chain
        counts ONCE instead of once per member. Page counts are sampled at
        dispatch time (the k fused steps may each cross at most one page
        boundary — a <1-page-per-slot estimate error, documented in the
        flight deck). ``k_tokens`` decouples the emission floor from the
        attention-row count for spec dispatches (m verify rows per round
        but >=1 emitted token per round)."""
        active_idx = np.flatnonzero(self._active)
        if active_idx.size == 0:
            return
        pages_tot = self._seq_lens[active_idx] // self.page_size + 1
        logical = int(pages_tot.sum())
        streamed = logical
        for live, n_pre, _pages in group_rows:
            streamed -= (len(live) - 1) * n_pre
        self.deck.on_kv_read(
            streamed * k, logical * k,
            int(active_idx.size) * (k if k_tokens is None else k_tokens))

    def _prefill_request(self, slot: int, req: _Request, pages: list[int],
                         budget: int, matched_pages: list[int] | None = None,
                         matched_entries: list | None = None,
                         own_prefix_pages: int = 0) -> None:
        """Fused async admission: the compiled prefill also inserts the slot
        into the device control state, and the first token's emission is
        deferred to the emit queue — no host round trip per request.
        ``own_prefix_pages``: leading entries of ``pages`` whose KV is
        ALREADY filled (chunked prefill's earlier chunks) — they join the
        attended prefix but, unlike cache-matched pages, belong to this
        request and get published as fresh pages."""
        matched_pages = matched_pages or []
        matched_entries = list(matched_entries or [])
        n_prompt = len(req.input_ids)
        prefix_pages_all = matched_pages + pages[:own_prefix_pages]
        prefix_len = len(prefix_pages_all) * self.page_size
        sp = req.sampling

        all_pages = matched_pages + pages
        row = np.zeros((self.pages_per_slot,), np.int32)
        row[:len(all_pages)] = all_pages
        stops = np.full((MAX_STOP_TOKENS,), -1, np.int32)
        for i, t in enumerate(sp.stop_token_ids[:MAX_STOP_TOKENS]):
            stops[i] = t

        self._ensure_dev_state()
        state_kwargs = {k: self._dev_state[k] for k in self._STATE_KEYS}
        use_filters = bool(sp.top_p < 1.0 or sp.top_k > 0)
        if prefix_pages_all:
            # prefix-cache hit and/or chunk-filled prefix: prefill only the
            # remaining suffix, attending over the filled pages
            suffix_len = n_prompt - prefix_len
            packed, pb, n_pre_b = self._pack_suffix(
                req.input_ids[prefix_len:], suffix_len, prefix_len,
                prefix_pages_all, pages[own_prefix_pages:], row, stops,
                slot, budget, sp)
            fn = self._get_prefill_suffix(pb, n_pre_b, use_filters)
        else:
            pb = next_bucket(n_prompt, self.prompt_buckets)
            n_prompt_pages = -(-n_prompt // self.page_size)
            page_ids = np.zeros((pb // self.page_size,), np.int32)
            page_ids[:n_prompt_pages] = pages[:n_prompt_pages]
            ids = np.full((pb,), self.pad_token_id, np.int32)
            ids[:n_prompt] = req.input_ids
            packed = self._pack_prefill(ids, page_ids, row, stops,
                                        np.zeros((0,), np.int32),
                                        n_prompt, 0, slot, budget, sp)
            fn = self._get_prefill(pb, use_filters)
        kp, vp, self._rng, token, logp, done, new_st = fn(
            self.params, self._pools[0], self._pools[1],
            jnp.asarray(packed), self._rng, **state_kwargs)
        self._pools = (kp, vp)
        self._carry_spec_state(new_st, [(slot, req.input_ids)])
        self._dev_state = new_st

        # publish the prompt's freshly computed full pages; ownership of
        # published pages moves to the cache (the slot holds refs)
        private = list(pages)
        if self.prefix_cache is not None:
            published = self.prefix_cache.publish(
                req.input_ids, all_pages, n_cached=len(matched_pages),
                matched_entries=matched_entries)
            pub_pages = {e.page for _, e in published}
            private = [p for p in pages if p not in pub_pages]
            matched_entries += [e for _, e in published]
            if self.kvledger is not None:
                self.kvledger.on_publish(pub_pages)
        self._consume_group_preref(req)
        self._register_group_prerefs(req, matched_entries)
        # singleton admission (leader, full/partial hit, chunk final): the
        # full prompt chain is cached after this dispatch's publish, so the
        # seat key is the first n_full page ids — identical across members
        self._register_decode_group(
            req, slot, max(0, (n_prompt - 1) // self.page_size), row)

        # host mirrors: everything except the (device-side) first token;
        # _emit_prefill fills last_tokens when the output is drained, and
        # finalizes immediately-finished requests
        self._page_table[slot] = row
        self._seq_lens[slot] = n_prompt
        self._last_tokens[slot] = self.pad_token_id
        self._n_generated[slot] = 1
        self._budgets[slot] = budget
        self._active[slot] = True
        self._temps[slot] = sp.temperature
        self._top_ps[slot] = sp.top_p
        self._top_ks[slot] = sp.top_k
        self._stop_table[slot] = stops
        self._slots[slot] = _SlotInfo(req, private, set(sp.stop_token_ids),
                                      cache_entries=matched_entries,
                                      admit_version=self.weight_version)
        if self._hist is not None:
            self._hist[slot] = list(req.input_ids)
        self._slot_gen[slot] += 1
        # cached_tokens = the prefix this dispatch did NOT compute (cache
        # hit and/or chunk-filled pages); the ledger's prefill total still
        # counts the full prompt — token accounting is about attribution,
        # not compute
        self.deck.on_admit(slot, req.rid, req.t_submit, n_prompt,
                           cached_tokens=prefix_len)
        self._enqueue_output(("prefill", (token, logp, done),
                             (slot, int(self._slot_gen[slot])),
                             self.weight_version))

    # -- device-resident state + pipelined stepping --------------------------

    def _invalidate_dev_state(self) -> None:
        self._dev_state = None

    def _carry_spec_state(self, new_st: dict,
                          admissions: list[tuple[int, list[int]]]) -> None:
        """Prefill dispatches return a fresh state dict without the spec
        token buffer — carry it over and write each newly admitted slot's
        PROMPT into its row (the device-sampled first token arrives via
        the spec step's last_tokens splice)."""
        if self._hist is None or self._dev_state is None:
            return
        buf = self._dev_state.get("tok_buf")
        if buf is None:
            return
        if admissions:
            # ONE batched scatter for the whole admission wave (per-slot
            # .at[].set would copy the full buffer once per request)
            slots = np.array([s for s, _ in admissions], np.int32)
            width = min(max(len(ids) for _, ids in admissions),
                        self.max_seq_len)
            rows = np.zeros((len(admissions), width), np.int32)
            keep = np.zeros((len(admissions), width), bool)
            for j, (_s, ids) in enumerate(admissions):
                n = min(len(ids), width)
                rows[j, :n] = ids[:n]
                keep[j, :n] = True
            cur = buf[jnp.asarray(slots), :width]
            buf = buf.at[jnp.asarray(slots), :width].set(
                jnp.where(jnp.asarray(keep), jnp.asarray(rows), cur))
        new_st["tok_buf"] = buf

    def _ensure_dev_state(self) -> None:
        if self._dev_state is not None:
            return
        # mirrors must be exact before a re-upload: queued emissions still
        # carry device-side first tokens (mirror last_tokens is a
        # placeholder until drained)
        self._drain_emit_q()
        # device state carries ONE extra row (index max_slots): the SINK —
        # admission-wave padding rows insert there (never active, pages all
        # null), so padded batch prefills can't collide with a real slot's
        # sampled token / active flag
        self._dev_state = {
            "page_table": jnp.asarray(np.concatenate(
                [self._page_table,
                 np.zeros((1, self.pages_per_slot), np.int32)])),
            "seq_lens": jnp.asarray(np.append(self._seq_lens, 0).astype(np.int32)),
            "last_tokens": jnp.asarray(np.append(
                self._last_tokens, self.pad_token_id).astype(np.int32)),
            "n_generated": jnp.asarray(np.append(self._n_generated, 0).astype(np.int32)),
            "budgets": jnp.asarray(np.append(self._budgets, 0).astype(np.int32)),
            "active": jnp.asarray(np.append(self._active, False)),
            "temps": jnp.asarray(np.append(self._temps, 1.0).astype(np.float32)),
            "top_ps": jnp.asarray(np.append(self._top_ps, 1.0).astype(np.float32)),
            "top_ks": jnp.asarray(np.append(self._top_ks, 0).astype(np.int32)),
            "stop_table": jnp.asarray(np.concatenate(
                [self._stop_table,
                 np.full((1, MAX_STOP_TOKENS), -1, np.int32)])),
        }
        if self._hist is not None:
            # spec token buffer (prompt + emitted per slot, front-filled),
            # rebuilt from the host history mirror
            buf = np.zeros((self.max_slots + 1, self.max_seq_len), np.int32)
            for i, h in enumerate(self._hist):
                if h:
                    n = min(len(h), self.max_seq_len)
                    buf[i, :n] = h[:n]
            self._dev_state["tok_buf"] = jnp.asarray(buf)


    def _enqueue_output(self, entry) -> None:
        """Queue a dispatch output for the fetcher thread (wakes it)."""
        with self._fetch_cv:
            self._emit_q.append(entry)
            self._fetch_cv.notify_all()

    def _outstanding(self) -> int:
        """Dispatch outputs not yet emitted (queued + in device_get + landed)."""
        with self._fetch_cv:
            return (len(self._emit_q) + self._fetch_inflight
                    + len(self._fetched_q))

    def _fetch_loop(self) -> None:
        """Fetcher thread: own the blocking device->host transfer. Grabs
        every queued output in one batched ``device_get`` (a get per entry
        would serialize a round trip each), then hands the host arrays back
        for the loop thread to emit. ``device_get`` releases the GIL during
        the transfer, so round trips overlap dispatching AND each other."""
        cv = self._fetch_cv
        while not self._stop.is_set():
            with cv:
                if not self._emit_q:
                    cv.wait(timeout=0.05)
                    continue
                # oldest half-window only: a get blocks until its NEWEST
                # entry finishes on device, so grabbing everything would
                # stall each round trip behind just-dispatched compute —
                # the older half is already done and returns in one RTT
                # while the newer half computes
                cap = max(1, self.pipeline_depth // 2)
                batch = [self._emit_q.popleft()
                         for _ in range(min(cap, len(self._emit_q)))]
                self._fetch_inflight = len(batch)
                epoch = self._fetch_epoch
            t0 = time.monotonic()
            handed_off = False
            try:
                try:
                    fetched = jax.device_get([e[1] for e in batch])
                except Exception as exc:  # noqa: BLE001 — surface on the
                    # loop thread (next drain) where _recover can reset
                    # pools; true BaseExceptions (SystemExit et al) must
                    # NOT be forwarded: _loop only recovers from Exception
                    with cv:
                        self._fetch_inflight = 0
                        if epoch == self._fetch_epoch:
                            self._fetch_exc = exc
                        cv.notify_all()
                    handed_off = True
                    continue
                self._tmark("fetch", t0)
                with cv:
                    self._fetched_q.extend(
                        (epoch, e, a) for e, a in zip(batch, fetched))
                    self._fetch_inflight = 0
                    cv.notify_all()
                handed_off = True
            finally:
                if not handed_off:
                    # a BaseException is killing this thread mid-batch:
                    # requeue the batch (front, preserving FIFO) and zero
                    # the inflight count so _drain_emit_q's accounting
                    # stays consistent and its dead-fetcher fallback can
                    # fetch these entries synchronously — otherwise the
                    # loop thread (and every HTTP handler) wedges forever
                    with cv:
                        for e in reversed(batch):
                            self._emit_q.appendleft(e)
                        self._fetch_inflight = 0
                        cv.notify_all()

    def _drain_emit_q(self, keep: int = 0) -> None:
        """Stream out every dispatch output the fetcher has landed, bringing
        the host mirrors up to date; block until at most ``keep`` outputs
        remain un-emitted. ``keep=0`` is the full barrier every dev-state
        re-upload needs; ``keep=pipeline_depth`` is the steady-state call
        that only throttles the loop when the device runs too far ahead."""
        if self._fetch_thread is None:
            # engine not started (unit tests drive internals directly):
            # fetch the oldest beyond ``keep`` synchronously on this thread
            self._fetch_sync(keep)
        cv = self._fetch_cv
        while True:
            with cv:
                ready = list(self._fetched_q)
                self._fetched_q.clear()
                exc, self._fetch_exc = self._fetch_exc, None
                epoch = self._fetch_epoch
            if ready:
                with self._phase("emit"):
                    for ep, entry, arrs in ready:
                        if ep == epoch:
                            self._emit_entry(entry, arrs)
            if exc is not None:
                raise exc
            with cv:
                if (len(self._emit_q) + self._fetch_inflight
                        + len(self._fetched_q) <= keep):
                    return
            fetcher_dead = (self._fetch_thread is not None
                            and not self._fetch_thread.is_alive())
            if self._stop.is_set() or fetcher_dead:
                # the fetcher exits on stop() even with entries queued — or
                # died on a BaseException (its finally requeued the batch
                # and zeroed inflight); finish the drain synchronously so
                # the loop thread can observe _stop / keep serving instead
                # of waiting out the timeout.
                # FIFO: if the fetcher still owns an older in-flight batch,
                # wait for it to land rather than fetching newer entries
                # past it (out-of-order emission corrupts the mirrors); the
                # queue grab happens under the SAME cv hold as the inflight
                # check so the fetcher cannot pop a batch in between
                with cv:
                    if self._fetch_inflight:
                        with self._phase("sample_fetch"):
                            cv.wait(timeout=0.2)
                        continue
                    # respect ``keep``: a dead fetcher must not turn the
                    # steady-state drain into a full barrier that stalls
                    # on just-dispatched device work
                    n = len(self._emit_q) - keep
                    batch = [self._emit_q.popleft()
                             for _ in range(max(0, n))]
                    epoch = self._fetch_epoch
                if batch:
                    with self._phase("sample_fetch"):
                        fetched = jax.device_get([e[1] for e in batch])
                    with cv:
                        self._fetched_q.extend(
                            (epoch, e, a) for e, a in zip(batch, fetched))
                continue
            with cv:
                if not self._fetched_q and (self._emit_q
                                            or self._fetch_inflight):
                    with self._phase("sample_fetch"):
                        cv.wait(timeout=0.2)

    def _fetch_sync(self, keep: int = 0) -> None:
        """Unthreaded fallback: move queued outputs beyond ``keep`` (oldest
        first) to _fetched_q — the pre-fetcher-thread drain semantics."""
        with self._fetch_cv:
            n = len(self._emit_q) - keep
            batch = [self._emit_q.popleft() for _ in range(max(0, n))]
            epoch = self._fetch_epoch
        if not batch:
            return
        t0 = time.monotonic()
        with self._phase("sample_fetch"):
            fetched = jax.device_get([e[1] for e in batch])
        self._tmark("fetch", t0)
        with self._fetch_cv:
            self._fetched_q.extend(
                (epoch, e, a) for e, a in zip(batch, fetched))

    def _emit_entry(self, entry, arrs) -> None:
        kind, _payload, tail = entry[:3]
        if kind in ("step", "spec"):
            for slot, gen in tail:
                # a finalized+reused slot zeroed its counter: stale
                # decrements for the old request must not starve the new
                if self._slot_gen[slot] == gen:
                    self._inflight_tok[slot] = max(
                        0, self._inflight_tok[slot] - entry[3])
        # dispatch-time weight version tag (last tuple element): the chunk
        # reports the policy that actually SAMPLED its tokens, not whatever
        # version is live when the fetch lands steps later
        wv = entry[-1]
        if kind == "step":
            self._emit_fetched(*arrs, tail, wv=wv)
        elif kind == "spec":
            token, logp, done, emitted = arrs
            self._emit_fetched(token, logp, done, tail, emitted=emitted,
                               wv=wv)
        elif kind == "prefillb":
            # batched admission wave: one output row per real request
            token, logp, done = arrs
            for j, slot_gen in enumerate(tail):
                self._emit_prefill(int(token[j]), float(logp[j]),
                                   bool(done[j]), slot_gen, wv)
        else:
            token, logp, done = arrs
            self._emit_prefill(int(token), float(logp), bool(done), tail, wv)

    def _emit_prefill(self, t: int, lp: float, device_done: bool,
                      tail: tuple[int, int], wv: int) -> None:
        """Deliver an admitted request's first token (deferred from the
        fused prefill dispatch)."""
        slot, gen = tail
        info = self._slots[slot]
        if info is None or self._slot_gen[slot] != gen:
            return
        stop_hit = t in info.stop_set
        fin = device_done or stop_hit
        reason = "stop" if stop_hit else ("length" if fin else "")
        info.req.out.put({"token_ids": [t], "logprobs": [lp],
                          "finished": fin, "finish_reason": reason,
                          "weight_version": wv})
        self._last_tokens[slot] = t
        info.emitted.append(t)
        if self._hist is not None:
            self._hist[slot].append(t)
        self.deck.on_first_token(slot)
        self._count_tokens(1)
        if fin:
            # finalize BEFORE the terminal marker: a client that saw
            # STREAM_END may read the flight deck immediately, so both
            # deck sides must already be folded (quiescence invariant).
            # finally: the terminal must reach the client even if finalize
            # raises (a deactivated slot is invisible to _recover's sweep)
            self._active[slot] = False
            try:
                self._finalize(slot)
            finally:
                info.req.out.put(STREAM_END)
            if not device_done:
                # stop token beyond the device table: device active is stale
                self._invalidate_dev_state()

    def _emit_fetched(self, token, logp, done, idxs, emitted=None,
                      wv: int = -1) -> None:
        """Stream one fetched dispatch ([k, slots] token/logp/done rows, one
        per fused step) to the requests; ``idxs`` is a list of (slot,
        generation) pairs and may be a superset of live slots (mirrors lag
        the pipeline by one step) — finished slots, slots that finished in
        an EARLIER row of this same dispatch (pad-token tail of the scan),
        and slots reused by a newer admission (generation mismatch) are all
        filtered. ``emitted`` ([rows, slots] bool, speculative dispatches
        only) masks rows a slot did not actually emit (rejected drafts)."""
        token, logp, done = (np.atleast_2d(np.asarray(a))
                             for a in (token, logp, done))
        if emitted is not None:
            emitted = np.atleast_2d(np.asarray(emitted))
        n_emitted = 0
        finished: list[int] = []
        host_stop_fix = False
        for r in range(token.shape[0]):
            for i, gen in idxs:
                info = self._slots[i]
                if info is None or not self._active[i] or self._slot_gen[i] != gen:
                    continue
                if emitted is not None and not emitted[r, i]:
                    continue
                t = int(token[r, i])
                # host check is authoritative: covers stop tokens beyond the
                # MAX_STOP_TOKENS device table
                fin = bool(done[r, i]) or t in info.stop_set
                reason = ""
                if fin:
                    reason = "stop" if t in info.stop_set else "length"
                info.req.out.put({"token_ids": [t],
                                  "logprobs": [float(logp[r, i])],
                                  "finished": fin, "finish_reason": reason,
                                  "weight_version": wv})
                n_emitted += 1
                self._seq_lens[i] += 1
                self._last_tokens[i] = t
                self._n_generated[i] += 1
                info.emitted.append(t)
                self.deck.on_decode(i)
                if self._hist is not None:
                    self._hist[i].append(t)
                if fin:
                    # deactivate now (later rows of this dispatch must skip
                    # the finished slot) but defer finalize + STREAM_END
                    self._active[i] = False
                    finished.append(i)
                    if not bool(done[r, i]):
                        # device missed this stop (beyond its table): its
                        # active mask is stale — force a state re-upload. Any
                        # step already in flight writes one garbage token into
                        # the freed pages, which is safe: a later prefill
                        # reusing them is ordered after it by the pools data
                        # dependency.
                        host_stop_fix = True
        if host_stop_fix:
            self._invalidate_dev_state()
        if emitted is not None:
            self.spec_emitted += n_emitted
        self._count_tokens(n_emitted)
        # terminal markers LAST: a client that saw STREAM_END may read the
        # flight deck immediately (quiescence reconciliation), so both the
        # scheduler-side total above and the per-request fold in _finalize
        # must land before the stream visibly ends
        for i in finished:
            info = self._slots[i]
            # finally: the terminal must reach the client even if finalize
            # raises — these slots are already inactive, so _recover's
            # _fail_all sweep would never release them
            try:
                self._finalize(i)
            finally:
                info.req.out.put(STREAM_END)
        self.num_running = int(self._active.sum())

    def _step_once(self) -> None:
        # host-side aborts flip slots inactive BEFORE the next dispatch;
        # mirrors must be current, so drain the pipeline first
        if any(info is not None and self._active[i]
               and info.req.abort is not None and info.req.abort.is_set()
               for i, info in enumerate(self._slots)):
            if self.salvage_partials:
                self._abort_with_salvage()
            else:
                self._abort_fast()

        if not self._active.any():
            self._drain_emit_q()
            return
        # tail cutoff: when every mirror-active slot's remaining budget is
        # already covered by dispatches in flight for that slot, another
        # dispatch could only compute pad rows — park on the fetcher until
        # a result lands instead. Exact for budget-bound streams (RL
        # rollouts with fixed max_new_tokens); stop-token finishes may
        # still run ahead a few dispatches (the device's early-out isn't
        # host-visible yet).
        rem = int(np.max((self._budgets - self._n_generated
                          - self._inflight_tok)[self._active]))
        if rem <= 0:
            out = self._outstanding()
            if out:
                self._drain_emit_q(keep=out - 1)
            return
        use_filters = bool(np.any(
            (self._top_ps[self._active] < 1.0) | (self._top_ks[self._active] > 0)))
        if self.spec_tokens > 0:
            self._spec_step_once(use_filters)
            return
        t0 = time.monotonic()
        with self._phase("decode_dispatch_device"):
            self._ensure_dev_state()
        self._tmark("upload", t0)
        st = self._dev_state
        # shared-prefix grouped decode: pack the live group tables (one
        # small int32 upload riding the dispatch — membership churn changes
        # DATA, not the compiled step, as long as the bucketed shape holds)
        gpack, gshape, group_rows = self._decode_group_pack()
        fn = self._get_step(use_filters, self.steps_per_dispatch, gshape)
        t0 = time.monotonic()
        args = (self.params, self._pools[0], self._pools[1], self._rng,
                st["page_table"], st["seq_lens"], st["last_tokens"],
                st["n_generated"], st["budgets"], st["active"], st["temps"],
                st["top_ps"], st["top_ks"], st["stop_table"])
        if gshape is not None:
            args = args + (jnp.asarray(gpack),)
            self.grouped_decode_dispatches += 1
        with self._phase("decode_dispatch_device"):
            (kp, vp, self._rng, token, logp, done, st["seq_lens"],
             st["last_tokens"], st["n_generated"], st["active"]) = fn(*args)
        self._tmark("step_dispatch", t0)
        self._pools = (kp, vp)
        with self._phase("accounting"):
            self._account_kv_reads(group_rows, self.steps_per_dispatch)
        self._inflight_tok[self._active] += self.steps_per_dispatch
        self._enqueue_output(("step", (token, logp, done),
                             [(int(i), int(self._slot_gen[i]))
                              for i in np.flatnonzero(self._active)],
                             self.steps_per_dispatch, self.weight_version))
        with self._phase("accounting"):
            self._deck_dispatch()
        # run ahead up to pipeline_depth dispatches: older outputs stream
        # out of the fetcher while the device computes, hiding the fetch
        # round trips entirely
        self._drain_emit_q(keep=self.pipeline_depth)

    def _abort_fast(self) -> None:
        # emit the abort terminal FIRST and bump the slot generation so
        # queued/in-flight results for the aborted stream are dropped at
        # emission — the client is released after one loop iteration,
        # not after the whole run-ahead pipeline streams out
        aborted: list[int] = []
        for i, info in enumerate(self._slots):
            if info is None or not self._active[i]:
                continue
            if info.req.abort is not None and info.req.abort.is_set():
                self._active[i] = False
                self._slot_gen[i] += 1
                self._emit_abort(info.req, emit_line=True)
                aborted.append(i)
        if aborted:
            # full barrier BEFORE freeing pages: in-flight dispatches
            # still write KV through the old device page table; pages
            # may only return to the pool once nothing references them.
            # finally: a raising drain goes to _recover, which rebuilds
            # the pools — the aborted slots must still be finalized or
            # their slots+pages leak (recover's _fail_all only sweeps
            # mirror-ACTIVE slots, and these were just marked inactive)
            try:
                self._drain_emit_q()
            finally:
                for i in aborted:
                    self._finalize(i, cause="abort")
                self._invalidate_dev_state()

    def _abort_with_salvage(self) -> None:
        """Partial-rollout salvage (token-level continuous generation): the
        aborted slots stay active through a full pipeline drain, so every
        token the in-flight dispatches already decoded streams out to the
        client instead of being dropped, THEN the terminal abort (the
        'partial' the manager's continuation and the trainer's salvage
        ledger resume from) is emitted. Same wall cost as the fast path —
        the full barrier was always needed before freeing pages — traded
        against fast-path abort latency (the client waits out the drain).
        Decoded full pages are published to the prefix cache so a
        continuation re-dispatched to THIS engine re-uses the KV."""
        aborted = [i for i, info in enumerate(self._slots)
                   if info is not None and self._active[i]
                   and info.req.abort is not None and info.req.abort.is_set()]
        before = {i: len(self._slots[i].emitted) for i in aborted}
        try:
            self._drain_emit_q()
        finally:
            for i in aborted:
                info = self._slots[i]
                if info is None or not self._active[i]:
                    continue  # finished (stop/budget) during the drain
                # tokens the fast path would have dropped (decoded by
                # in-flight dispatches, streamed out by the drain above)
                self.tokens_salvaged += len(info.emitted) - before[i]
                self._active[i] = False
                self._slot_gen[i] += 1
                # terminal AFTER the fold: the drain above already released
                # every salvaged token, so this costs no client latency —
                # and a client that saw the abort terminal reads a deck
                # whose request side includes this slot (quiescence).
                # finally: the terminal must still reach the client if any
                # of the salvage bookkeeping raises (slot already inactive,
                # so _recover's _fail_all sweep would never release it)
                try:
                    self._salvage_publish(i, info)
                    self.deck.on_salvage(i)
                    self._finalize(i, cause="salvage")
                finally:
                    self._emit_abort(info.req, emit_line=True)
            self._invalidate_dev_state()

    def _salvage_publish(self, slot: int, info: _SlotInfo) -> None:
        """Publish an aborted slot's full pages (prompt + generated tokens)
        into the prefix cache: the continuation request's prompt IS this
        token sequence, so its suffix prefill matches these pages and skips
        recomputing the decoded KV. Decode-written KV equals prefill KV for
        the same tokens/positions under the same weights; a slot admitted
        under an older weight version is skipped (its KV predates the flush
        a weight swap performs)."""
        if (self.prefix_cache is None
                or info.admit_version != self.weight_version
                or not info.emitted):
            return
        seq = list(info.req.input_ids) + [int(t) for t in info.emitted]
        n_full = max(0, (len(seq) - 1) // self.page_size)
        if n_full == 0:
            return
        page_row = [int(p) for p in self._page_table[slot][:n_full]]
        matched_pages, matched_entries = self.prefix_cache.match(seq)
        if self.kvspill is not None and any(e.spilled
                                            for e in matched_entries):
            # salvage must not pay a restore just to dedup its publish:
            # truncate the verified chain at the first spilled entry —
            # publish walks the rest against the existing (spilled)
            # entries by token + parent identity, pages stay slot-private
            cut = next(i for i, e in enumerate(matched_entries)
                       if e.spilled)
            self.prefix_cache.release(matched_entries[cut:])
            matched_pages = matched_pages[:cut]
            matched_entries = matched_entries[:cut]
        published = self.prefix_cache.publish(
            seq, page_row, n_cached=len(matched_pages),
            matched_entries=matched_entries)
        # ownership of published pages moves to the cache; the rest of the
        # slot's private pages are freed by _finalize as usual
        pub_pages = {e.page for _, e in published}
        info.pages = [p for p in info.pages if p not in pub_pages]
        self.salvage_published_pages += len(pub_pages)
        if self.kvledger is not None:
            self.kvledger.on_publish(pub_pages)
        # drop the refs this publish round took (match + publish): the
        # entries stay resident, unreferenced, LRU-evictable — exactly the
        # state admission-published pages reach after their slot finalizes
        self.prefix_cache.release(matched_entries + [e for _, e in published])

    def _spec_step_once(self, use_filters: bool) -> None:
        """One speculative decode dispatch: spec_rounds fused rounds of
        device-side propose→verify→accept. Fully device-resident (the
        token history lives in dev state), so spec dispatches pipeline
        exactly like fused normal steps — outputs drain lazily while the
        device runs ahead."""
        m = self.spec_tokens + 1
        t0 = time.monotonic()
        with self._phase("decode_dispatch_device"):
            self._ensure_dev_state()
        self._tmark("upload", t0)
        st = self._dev_state
        fn = self._get_spec_step(use_filters, m, self.spec_rounds)
        t0 = time.monotonic()
        with self._phase("decode_dispatch_device"):
            (kp, vp, self._rng, st["tok_buf"], token, logp, done, emitted,
             st["seq_lens"], st["last_tokens"], st["n_generated"],
             st["active"]) = fn(
                self.params, self._pools[0], self._pools[1], self._rng,
                st["tok_buf"], st["page_table"], st["seq_lens"],
                st["last_tokens"], st["n_generated"], st["budgets"],
                st["active"], st["temps"], st["top_ps"], st["top_ks"],
                st["stop_table"])
        self._tmark("spec_dispatch", t0)
        self._pools = (kp, vp)
        # spec verify attends m virtual rows per slot per round, all over
        # the slot's own pages (grouped decode is decode-path only);
        # tokens normalized by the >=1-per-round emission floor
        with self._phase("accounting"):
            self._account_kv_reads((), self.spec_rounds * m,
                                   k_tokens=self.spec_rounds)
        self.spec_dispatches += 1
        # acceptance ceiling: every active slot could emit up to
        # rounds * (spec_tokens+1) tokens from this dispatch
        self.spec_token_ceiling += (int(self._active.sum())
                                    * self.spec_rounds * m)
        # each spec round emits >=1 token per still-active slot
        self._inflight_tok[self._active] += self.spec_rounds
        self._enqueue_output(("spec", (token, logp, done, emitted),
                             [(int(i), int(self._slot_gen[i]))
                              for i in np.flatnonzero(self._active)],
                             self.spec_rounds, self.weight_version))
        with self._phase("accounting"):
            self._deck_dispatch()
        self._drain_emit_q(keep=self.pipeline_depth)

    def _deck_dispatch(self) -> None:
        """Scheduler step-ledger sample at decode-dispatch time: occupancy,
        page pressure, prefix-cache residency, run-ahead depth."""
        self.deck.on_dispatch(
            int(self._active.sum()), self.allocator.free_count,
            self.prefix_cache.num_entries
            if self.prefix_cache is not None else 0,
            self._outstanding(), len(self._pending))
        if self.kvledger is not None:
            # touch every active slot's page row (the pages this dispatch's
            # attention logically reads — cache-matched prefix included)
            # and re-sweep the hot/warm/cold residency tiers. Page-0
            # padding in the rows is filtered; the reserved role would
            # keep it out of the tier counts anyway.
            rows = self._page_table[self._active].ravel()
            self.kvledger.on_dispatch(rows[rows != 0])
            if self.kvspill is not None:
                # host-RAM spill sweep rides the same off-hot-path seam:
                # page util over the high watermark pages the coldest
                # unreferenced published pages out to host
                self._spill_sweep()

    @property
    def spec_accept_rate(self) -> float:
        """Speculative acceptance: emitted tokens over the dispatches'
        token ceiling (each active slot could emit rounds*(spec_tokens+1)
        per dispatch). 0.0 before any spec dispatch; 1/(spec_tokens+1)
        per round is the no-acceptance floor, 1.0 the perfect-lookup
        ceiling."""
        if self.spec_token_ceiling <= 0:
            return 0.0
        return self.spec_emitted / self.spec_token_ceiling

    def _finalize(self, slot: int, cause: str = "finalize") -> None:
        self.deck.on_finalize(slot)
        # leave the decode group FIRST: the next dispatch must not seat a
        # finalized slot (its freed pages may be reallocated; in-flight
        # dispatches that still carry the old seat only produce garbage for
        # this now-inactive slot, which emission filters)
        self._drop_decode_seat(slot)
        info = self._slots[slot]
        if info is not None:
            self.allocator.free(info.pages)
            if self.kvledger is not None:
                # cause: "finalize" for natural completion, "abort"/
                # "salvage" when the abort paths finalize the slot
                self.kvledger.on_free(info.pages, cause)
            if self.prefix_cache is not None and info.cache_entries:
                self.prefix_cache.release(info.cache_entries)
            # per-request serving telemetry: submit→finalize wall and the
            # request's effective decode rate (continuous batching means
            # every request has its OWN elapsed time, unlike the bucketed
            # engine's shared batch clock)
            dt = time.monotonic() - info.req.t_submit
            n = int(self._n_generated[slot])
            if dt > 0 and n > 0:
                obs.observe("rollout/decode_tok_s", n / dt)
                obs.observe("rollout/request_s", dt)
        self._slots[slot] = None
        self._page_table[slot] = 0
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = self.pad_token_id
        self._n_generated[slot] = 0
        self._budgets[slot] = 0
        self._inflight_tok[slot] = 0
        if self._hist is not None:
            self._hist[slot] = None

    # -- emission helpers ----------------------------------------------------

    def _emit_abort(self, req: _Request, emit_line: bool = True) -> None:
        if emit_line:
            req.out.put({"token_ids": [], "logprobs": [], "finished": True,
                         "finish_reason": "abort"})
        req.out.put(STREAM_END)

    def _emit_error(self, req: _Request, msg: str) -> None:
        req.out.put({"token_ids": [], "logprobs": [], "finished": True,
                     "finish_reason": "error", "error": msg})
        req.out.put(STREAM_END)

    def _fail_all(self, msg: str, finish_reason: str = "error") -> None:
        for i in np.flatnonzero(self._active):
            info = self._slots[i]
            self._active[i] = False
            if info is not None:
                if finish_reason == "abort":
                    self._emit_abort(info.req)
                else:
                    self._emit_error(info.req, msg)
            self._finalize(i, cause="abort")

    def _count_tokens(self, n: int) -> None:
        self.total_tokens_served += n
        if n > 0:
            # scheduler-side emission total (reconciles against per-request
            # decode counts at quiescence — flight-deck invariant)
            self.deck.on_emitted(n)
        now = time.monotonic()
        self._tok_window.append((now, n))
        horizon = now - 10.0
        toks = sum(c for t, c in self._tok_window if t >= horizon)
        t_old = min((t for t, _ in self._tok_window if t >= horizon), default=now)
        dt = now - t_old
        # a burst of emissions after a pipeline stall spans ~0 s; a rate
        # over that sliver is meaningless (and once polluted the serving
        # bench's peak metric) — only update over a meaningful span
        if dt >= 0.2:
            self.last_gen_throughput = self._tput_ewma.update(toks / dt, now)

    # -- convenience (tests / bench) ----------------------------------------

    def generate(self, prompt_ids: list[list[int]], sampling: SamplingParams,
                 timeout: float = 300.0, rng=None) -> list[dict]:
        """Synchronous batch generate: submit all, run the loop inline if not
        started, collect full sequences. Returns per-prompt dicts with
        token_ids / logprobs / finish_reason. ``rng`` is accepted for
        interface parity with RolloutEngine; the CB engine owns per-slot
        sampling state (admission order is not deterministic anyway)."""
        outs = [self.submit(f"gen-{i}", p, sampling)
                for i, p in enumerate(prompt_ids)]
        self.start()
        results = []
        deadline = time.monotonic() + timeout
        for out_q in outs:
            toks: list[int] = []
            lps: list[float] = []
            wvs: list[int] = []
            reason = "error"
            while True:
                item = out_q.get(timeout=max(0.0, deadline - time.monotonic()))
                if item is STREAM_END:
                    break
                toks.extend(item["token_ids"])
                lps.extend(item["logprobs"])
                # each chunk carries the version that sampled it; expanded
                # per token here so colocated trainers see the same
                # weight_versions the wire protocol streams (a weight swap
                # mid-request legitimately makes these mixed)
                wvs.extend([int(item.get("weight_version", -1))]
                           * len(item["token_ids"]))
                if item["finished"]:
                    reason = item["finish_reason"]
            results.append({"token_ids": toks, "logprobs": lps,
                            "weight_versions": wvs,
                            "finish_reason": reason})
        return results
