"""Trainer entry point: ``python -m polyrl_tpu.train [--config run.yaml]
[section.field=value ...]``.

Equivalent of the reference's C1 trainer driver (``python -m
rlboost.verl_stream.trainer.main_stream``, main_stream.py:40-94): compose
config, build datasets/tokenizer/reward, spawn the rollout manager when
disaggregated (head-node role, main_stream.py:342-362), assemble the
trainer, run ``fit``. The colocated mode is the ``main_ppo`` synchronous
baseline (SURVEY.md §3.5) behind the same flag surface
(``rollout.mode=colocated``).
"""

from __future__ import annotations

import argparse
import importlib.util
import logging
import os
import sys

from polyrl_tpu.config import RunConfig, load_config, to_dict

log = logging.getLogger("polyrl_tpu.train")


def build_tokenizer(cfg: RunConfig):
    from polyrl_tpu.utils.tokenizer import ByteTokenizer, load_tokenizer

    if cfg.tokenizer.kind == "byte":
        return ByteTokenizer()
    return load_tokenizer(cfg.tokenizer.name_or_path)


def build_dataset(cfg: RunConfig, split: str = "train"):
    from polyrl_tpu.data.dataset import RLDataset, make_arithmetic_dataset

    path = cfg.data.train_path if split == "train" else cfg.data.val_path
    if not path:
        return None
    if path == "arithmetic":
        return make_arithmetic_dataset(cfg.data.arithmetic_size, seed=cfg.data.seed)
    if path.endswith(".jsonl"):
        return RLDataset.from_jsonl(path)
    if path.endswith(".parquet"):
        return RLDataset.from_parquet(path, prompt_key=cfg.data.prompt_key)
    raise ValueError(f"unsupported dataset path {path!r}")


def load_custom_score(path: str):
    """Load ``compute_score`` from a user file (reference custom reward fn,
    reward.py:95-150)."""
    spec = importlib.util.spec_from_file_location("polyrl_custom_reward", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.compute_score


def _build_model(cfg: RunConfig):
    import jax
    import jax.numpy as jnp

    from polyrl_tpu.models import decoder

    if cfg.model.hf_path:
        from polyrl_tpu.models.hf_loader import build_from_hf

        mcfg, params = build_from_hf(cfg.model.hf_path,
                                     dtype=getattr(jnp, cfg.model.dtype),
                                     overrides=cfg.model.overrides)
        log.info("loaded pretrained weights from %s", cfg.model.hf_path)
        return mcfg, params
    mcfg = decoder.get_config(cfg.model.preset, dtype=getattr(jnp, cfg.model.dtype),
                              **cfg.model.overrides)
    params = jax.jit(lambda: decoder.init_params(
        jax.random.PRNGKey(cfg.trainer.seed), mcfg))()
    return mcfg, params


def _build_rollout(cfg: RunConfig, mcfg, params, tokenizer, cleanup: list):
    """Colocated: an in-process engine. Disaggregated: ManagerClient (+
    locally spawned manager when no endpoint is configured) + weight fabric;
    rollout instances join the pool on their own via
    ``python -m polyrl_tpu.rollout.serve``."""
    import jax.numpy as jnp

    if cfg.trainer.weight_sync == "lora_delta":
        # all delta-sync config validation BEFORE any manager spawn (the
        # fail-fast convention build_trainer documents for the SP block)
        if cfg.rollout.mode != "disaggregated":
            raise NotImplementedError(
                "weight_sync=lora_delta requires rollout.mode=disaggregated "
                "(a colocated in-process engine holds the plain tree; "
                "adapter pushes target workers serving --lora-rank)")
        if cfg.actor.lora_rank <= 0:
            raise ValueError(
                "trainer.weight_sync=lora_delta requires actor.lora_rank>0")
        if cfg.rollout.colocated_local:
            raise NotImplementedError(
                "weight_sync=lora_delta with colocated_local is not "
                "supported: the in-process engine serves the plain merged "
                "tree and cannot take adapter-only pushes")

    kv_dtype = getattr(jnp, cfg.rollout.kv_cache_dtype or cfg.model.dtype)
    pad = tokenizer.pad_token_id

    if cfg.rollout.mode == "colocated":
        if cfg.rollout.backend == "cb":
            from polyrl_tpu.rollout.cb_engine import CBEngine

            kwargs = {}
            if cfg.rollout.prompt_buckets:
                kwargs["prompt_buckets"] = tuple(cfg.rollout.prompt_buckets)
            return CBEngine(
                mcfg, params, pad_token_id=pad, kv_cache_dtype=kv_dtype,
                max_slots=cfg.rollout.max_slots, page_size=cfg.rollout.page_size,
                max_seq_len=cfg.rollout.max_seq_len,
                prefill_chunk=cfg.rollout.prefill_chunk,
                salvage_partials=cfg.rollout.salvage_partials,
                admit_wave=cfg.rollout.admit_wave,
                admit_reorder_window=cfg.rollout.admit_reorder_window,
                group_share=cfg.rollout.group_share,
                decode_group_share=cfg.rollout.decode_group_share,
                group_preref_ttl_s=cfg.rollout.group_preref_ttl_s,
                kv_ledger=cfg.rollout.kv_ledger,
                kv_cold_after_dispatches=(
                    cfg.rollout.kv_cold_after_dispatches),
                kv_spill=cfg.rollout.kv_spill,
                kv_spill_host_gb=cfg.rollout.kv_spill_host_gb,
                kv_spill_high_watermark=cfg.rollout.kv_spill_high_watermark,
                kv_spill_low_watermark=(
                    cfg.rollout.kv_spill_low_watermark),
                loop_profile=cfg.rollout.loop_profile, **kwargs)
        from polyrl_tpu.rollout.engine import RolloutEngine

        kwargs = {}
        if cfg.rollout.batch_buckets:
            kwargs["batch_buckets"] = tuple(cfg.rollout.batch_buckets)
        if cfg.rollout.prompt_buckets:
            kwargs["prompt_buckets"] = tuple(cfg.rollout.prompt_buckets)
        return RolloutEngine(mcfg, params, pad_token_id=pad,
                             kv_cache_dtype=kv_dtype, **kwargs)

    if cfg.rollout.mode != "disaggregated":
        raise ValueError(f"unknown rollout.mode {cfg.rollout.mode!r}")

    from polyrl_tpu.manager.client import ManagerClient
    from polyrl_tpu.manager.supervisor import ManagerSupervisor
    from polyrl_tpu.rollout.remote import RemoteRollout
    from polyrl_tpu.transfer import TransferInterface

    fault = None
    if cfg.rollout.fault_injection.enabled:
        # chaos mode: one injector shared by the trainer-side stream
        # wrapper and (below) the colocated local server
        from polyrl_tpu.rollout.faults import FaultInjector

        fault = FaultInjector(cfg.rollout.fault_injection)
        log.warning("rollout fault injection ENABLED: %s",
                    cfg.rollout.fault_injection)

    endpoint = cfg.rollout.manager_endpoint
    if not endpoint:
        # locally spawned manager runs SUPERVISED: crash/health failure →
        # backoff respawn + /reconcile state replay, and the client below
        # re-resolves the fresh ephemeral port through the supervisor
        supervisor = ManagerSupervisor(
            extra_args=list(cfg.rollout.manager_args),
            respawn_backoff_s=cfg.rollout.manager_respawn_backoff_s,
            respawn_backoff_max_s=cfg.rollout.manager_respawn_backoff_max_s,
        ).start()
        cleanup.append(supervisor.stop)
        mgr = supervisor.client()
        log.info("spawned supervised rollout manager on %s (log: %s)",
                 supervisor.endpoint, supervisor.log_path)
    else:
        mgr = ManagerClient(endpoint)
    mgr.wait_healthy()
    template = params
    if cfg.trainer.weight_sync == "lora_delta":
        # LoRA delta sync: the wire carries ONLY adapters (~rank/hidden of
        # the model); workers must serve with the matching --lora-rank
        # (combination validated fail-fast at the top of this function)
        from polyrl_tpu.models import lora as lora_mod

        template = lora_mod.adapter_template(mcfg, cfg.actor.lora_rank)
    transfer_fault = None
    if cfg.transfer.fault_injection.enabled:
        # transfer-plane chaos: frame corruption / stream stalls /
        # control-channel kills on the weight-push fabric
        from polyrl_tpu.rollout.faults import TransferFaultInjector

        transfer_fault = TransferFaultInjector(cfg.transfer.fault_injection)
        log.warning("transfer fault injection ENABLED: %s",
                    cfg.transfer.fault_injection)
    iface = TransferInterface(
        template, manager_client=mgr,
        num_streams=cfg.rollout.transfer_streams,
        advertise_host=cfg.rollout.advertise_host,
        sender_groups=cfg.rollout.sender_groups,
        sender_nic_cidr=cfg.rollout.sender_nic_cidr,
        groups_per_sender=cfg.rollout.groups_per_sender,
        cfg=cfg.transfer, fault=transfer_fault)
    cleanup.append(iface.close)

    local_server = None
    if cfg.rollout.colocated_local:
        # hybrid mode: an in-process engine shares this chip with training
        # and registers as a LOCAL instance — the manager time-slices it
        # (abort after the balancer window) and RemoteRollout releases /
        # resumes its KV HBM around the generation phase (reference
        # sglang_http_async_engine.py:43-113 + stream_fsdp_workers.py:468-492)
        from polyrl_tpu.rollout.cb_engine import CBEngine
        from polyrl_tpu.rollout.server import RolloutServer

        eng = CBEngine(
            mcfg, params, pad_token_id=pad, kv_cache_dtype=kv_dtype,
            max_slots=cfg.rollout.max_slots, page_size=cfg.rollout.page_size,
            max_seq_len=cfg.rollout.max_seq_len,
            prefill_chunk=cfg.rollout.prefill_chunk,
            spec_tokens=cfg.rollout.spec_tokens,
            spec_rounds=cfg.rollout.spec_rounds,
            salvage_partials=cfg.rollout.salvage_partials,
            admit_wave=cfg.rollout.admit_wave,
            admit_reorder_window=cfg.rollout.admit_reorder_window,
            group_share=cfg.rollout.group_share,
            decode_group_share=cfg.rollout.decode_group_share,
            group_preref_ttl_s=cfg.rollout.group_preref_ttl_s,
            kv_ledger=cfg.rollout.kv_ledger,
            kv_cold_after_dispatches=cfg.rollout.kv_cold_after_dispatches,
            kv_spill=cfg.rollout.kv_spill,
            kv_spill_host_gb=cfg.rollout.kv_spill_host_gb,
            kv_spill_high_watermark=cfg.rollout.kv_spill_high_watermark,
            kv_spill_low_watermark=cfg.rollout.kv_spill_low_watermark,
            loop_profile=cfg.rollout.loop_profile,
            **({"prompt_buckets": tuple(cfg.rollout.prompt_buckets)}
               if cfg.rollout.prompt_buckets else {}))
        local_server = RolloutServer(eng, host="127.0.0.1", port=0)
        local_server.fault = fault
        local_server.start()
        cleanup.append(local_server.stop)
        # register through the trainer's client (not a fresh one): the
        # supervisor then records the local endpoint for replay after a
        # manager respawn
        mgr.register_local_rollout_instances([local_server.endpoint])
        log.info("colocated local engine registered at %s",
                 local_server.endpoint)
    # fleet control plane: membership sweeps for /statusz + pool/* step
    # gauges, scale-up join gating, and preemption drills (rollout/pool.py)
    from polyrl_tpu.rollout.pool import PoolManager

    pool = PoolManager(mgr, cfg.rollout.pool)
    cleanup.append(pool.close)
    # weight-fabric supervision loop closure (ARCHITECTURE.md
    # "Weight-fabric fault tolerance"): a receiver that exhausts its push
    # retry budget is drained + deregistered by the fleet control plane,
    # and the sender's per-engine sync health rides the /statusz pool
    # section's engine rows
    iface.set_laggard_callback(pool.escalate_laggard)
    pool.transfer_health_fn = iface.sync_health
    return RemoteRollout(mgr, transfer=iface, local_server=local_server,
                         pad_token_id=pad,
                         resume_budget=cfg.rollout.resume_budget,
                         resume_wait_s=cfg.rollout.resume_wait_s,
                         salvage_partials=cfg.rollout.salvage_partials,
                         fault_injector=fault,
                         balance_window=cfg.rollout.pool.balance_window,
                         pool=pool)


def _build_mesh(cfg: RunConfig):
    """Build the global GSPMD mesh when parallelism is configured or the run
    is multi-process (jax.distributed). Returns None single-chip — the
    actor then skips sharding entirely."""
    import jax

    from polyrl_tpu.parallel import distributed
    from polyrl_tpu.parallel import mesh as meshlib

    p = cfg.parallel
    axes = (p.dp, p.fsdp, p.tp, p.sp, p.ep, p.pp)
    if jax.process_count() == 1 and all(a == 1 for a in axes):
        return None
    fsdp = p.fsdp
    if all(a == 1 for a in axes):
        # multi-process with no axes configured: absorb the global device
        # count into fsdp (MeshConfig's own default) so a plain multi-host
        # launch works without hand-set parallel: overrides
        fsdp = -1
    mcfg = meshlib.MeshConfig(dp=p.dp, fsdp=fsdp, tp=p.tp, sp=p.sp,
                              pp=p.pp, ep=p.ep)
    mesh = distributed.make_hybrid_mesh(config=mcfg)
    log.info("mesh: %s over %d devices (%d processes)",
             dict(zip(mesh.axis_names, mesh.devices.shape)),
             jax.device_count(), jax.process_count())
    return mesh


def build_trainer(cfg: RunConfig, cleanup: list | None = None):
    """Assemble the full trainer from a RunConfig. ``cleanup`` collects
    teardown callables (spawned manager, fabric threads)."""
    from polyrl_tpu.data.dataset import PromptDataLoader
    from polyrl_tpu.parallel import multihost
    from polyrl_tpu.rewards.manager import load_reward_manager
    from polyrl_tpu.trainer.actor import ReferencePolicy, StreamActor
    from polyrl_tpu.trainer.critic import StreamCritic, init_critic_params
    from polyrl_tpu.trainer.stream_trainer import StreamRLTrainer
    from polyrl_tpu.utils.metrics import Tracking

    cleanup = [] if cleanup is None else cleanup
    # observability first: spans opened during bring-up (manager spawn,
    # fabric registration) should already land in the ring buffer. The
    # trace dir defaults next to the JSONL metrics so the Perfetto dump
    # sits beside the run's step records.
    from polyrl_tpu import obs

    trace_dir = cfg.obs.trace_dir
    if not trace_dir and cfg.obs.trace and cfg.logging.path:
        trace_dir = os.path.dirname(os.path.abspath(cfg.logging.path))
    obs.configure(trace=cfg.obs.trace, max_spans=cfg.obs.trace_buffer,
                  out_dir=trace_dir or None,
                  jax_annotations=cfg.obs.jax_annotations)
    tokenizer = build_tokenizer(cfg)
    mesh = _build_mesh(cfg)
    mcfg, params = _build_model(cfg)

    # SP attention setup + config validation FIRST: a bad combination must
    # fail before the manager/fabric/reward workers are spawned and torn
    # back down on every attempt
    attn_fn = None
    packed_attn_fn = None
    sp_in_pipeline = False
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        # long-context: shard the sequence dim with a dedicated SP attention
        # (Ulysses all-to-all / ring ppermute) instead of whatever GSPMD
        # derives for dense attention over a sharded seq axis
        from polyrl_tpu.parallel.sequence import make_sp_attention

        sp = mesh.shape["sp"]
        # SP × TP composes: the SP attention keeps the head dim sharded
        # over tp (parallel/sequence.py specs), so tp-sharded projections
        # feed in with no head all-gather. Ulysses all-to-alls each tp
        # shard's LOCAL heads over sp → needs num_heads % (tp*sp) == 0;
        # ring never moves heads, so it has no extra constraint.
        tp = mesh.shape.get("tp", 1)
        if cfg.parallel.sp_mode == "ulysses" and mcfg.num_heads % (sp * tp):
            raise ValueError(
                f"ulysses SP needs num_heads ({mcfg.num_heads}) divisible "
                f"by sp*tp ({sp}*{tp}); use sp_mode=ring or different axes")
        if mesh.shape.get("pp", 1) > 1:
            # sp × pp: decoder.forward routes the whole stack through the
            # pipeline layers_fn, so the SP attention must live INSIDE the
            # stages — ring does (ring_attention_local in the pipeline's
            # {pp, sp}-manual region); Ulysses' head all-to-all would
            # reshard every stage boundary and is not implemented there.
            if cfg.parallel.sp_mode != "ring":
                raise NotImplementedError(
                    "parallel.sp > 1 with parallel.pp > 1 requires "
                    "sp_mode=ring (stage attention rings over sp inside "
                    f"the pipeline); got {cfg.parallel.sp_mode!r}")
            t_total = (cfg.trainer.max_prompt_length
                       + cfg.trainer.max_response_length)
            if t_total % sp:
                raise ValueError(
                    f"sp×pp needs max_prompt+max_response ({t_total}) "
                    f"divisible by sp ({sp})")
            sp_in_pipeline = True
        else:
            attn_fn = make_sp_attention(mesh, cfg.parallel.sp_mode)
            if cfg.trainer.use_remove_padding:
                # packed (remove-padding) long-context training composes
                # with SP via the segment-aware variant — the reference's
                # default long-context configuration (Ulysses over PACKED
                # varlen inputs, stream_dp_actor.py:37-47,135). The trainer
                # rounds pack_len up to a multiple of sp (_pack_geometry).
                # Only ulysses/ring have the segment-aware path; 'dense'
                # under sp>1 would silently hand GSPMD an unvalidated
                # composition.
                if cfg.parallel.sp_mode not in ("ulysses", "ring"):
                    raise NotImplementedError(
                        "use_remove_padding with parallel.sp > 1 requires "
                        "sp_mode=ulysses or ring (segment-aware SP "
                        f"attention); got sp_mode={cfg.parallel.sp_mode!r}")
                packed_attn_fn = make_sp_attention(
                    mesh, cfg.parallel.sp_mode, packed=True)

    layers_fn = None
    critic_layers_fn = None
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        # pipeline-parallel layer stack (parallel/pipeline.py): validate the
        # combination up front, same rationale as the SP block above.
        # packed × pp composes (stage attention takes per-batch segment
        # ids); sp × pp composes via sp_ring (validated above).
        from polyrl_tpu.parallel.pipeline import make_pipeline_layers_fn

        pp = mesh.shape["pp"]
        n_micro = cfg.parallel.pp_microbatches or 2 * pp
        if cfg.trainer.micro_batch_size % n_micro != 0:
            # not strictly required (the pipeline pads ragged feeds), but a
            # micro size that never fills the microbatches wastes the whole
            # configured pipeline width every step — treat as a config error
            raise ValueError(
                f"micro_batch_size {cfg.trainer.micro_batch_size} not "
                f"divisible by pp_microbatches {n_micro}")
        layers_fn = make_pipeline_layers_fn(mesh, mcfg, n_micro,
                                            remat=cfg.actor.remat,
                                            sp_ring=sp_in_pipeline)
        critic_layers_fn = make_pipeline_layers_fn(mesh, mcfg, n_micro,
                                                   remat=cfg.critic.remat,
                                                   sp_ring=sp_in_pipeline)

    if multihost.is_main():
        rollout = _build_rollout(cfg, mcfg, params, tokenizer, cleanup)
    else:
        # non-main hosts never open manager/fabric connections — batches
        # arrive via the trainer's broadcast plane (parallel/multihost.py)
        rollout = multihost.NullRollout(pad_token_id=tokenizer.pad_token_id)

    compute_score = (load_custom_score(cfg.reward.custom_score_path)
                     if cfg.reward.custom_score_path else None)
    if compute_score is None and cfg.reward.sandbox_url:
        # pod-scale code RL: ship code execution to the sandbox service,
        # bounded by a concurrency semaphore (reference reward.py:95-150)
        from polyrl_tpu.rewards.sandbox import SandboxClient

        compute_score = SandboxClient(
            cfg.reward.sandbox_url,
            max_concurrent=cfg.reward.sandbox_max_concurrent,
            timeout_s=cfg.reward.sandbox_timeout_s,
            memory_limit_mb=cfg.reward.sandbox_memory_limit_mb,
        ).compute_score
    reward_manager = load_reward_manager(
        cfg.reward.manager, tokenizer, compute_score=compute_score,
        num_workers=cfg.reward.num_workers)

    dataset = build_dataset(cfg, "train")
    loader = PromptDataLoader(dataset, cfg.trainer.train_batch_size,
                              shuffle=cfg.data.shuffle, seed=cfg.data.seed)

    actor = StreamActor(mcfg, cfg.actor, params, mesh=mesh, attn_fn=attn_fn,
                        layers_fn=layers_fn, packed_attn_fn=packed_attn_fn)
    critic = None
    if cfg.trainer.adv_estimator == "gae":
        import jax

        critic = StreamCritic(mcfg, cfg.critic, init_critic_params(
            jax.random.PRNGKey(cfg.trainer.seed + 1), mcfg), mesh=mesh,
            attn_fn=attn_fn, layers_fn=critic_layers_fn,
            packed_attn_fn=packed_attn_fn)
    # ReferencePolicy stays mesh-FREE deliberately: its params are a local
    # replicated copy and its feeds arrive as host numpy on every process —
    # a mesh-bound shard_map attn_fn would drag the global mesh into a
    # computation that must stay process-local in multi-host runs
    ref_policy = (ReferencePolicy(mcfg, params)
                  if (cfg.trainer.use_kl_in_reward or cfg.actor.use_kl_loss)
                  else None)
    logger = Tracking(backends=tuple(cfg.logging.backends),
                      path=cfg.logging.path or None)

    recorder = None
    if cfg.obs.recorder and multihost.is_main():
        # anomaly flight recorder (obs/recorder.py): watches the step
        # stream; an anomaly/crash/SIGTERM dumps a post-mortem bundle
        # (trace ring + step records + thread stacks) into the run dir
        from polyrl_tpu.obs.recorder import FlightRecorder

        rec_dir = (cfg.obs.recorder_dir
                   or (os.path.dirname(os.path.abspath(cfg.logging.path))
                       if cfg.logging.path else "polyrl_postmortem"))
        recorder = FlightRecorder(
            rec_dir, keep_steps=cfg.obs.recorder_keep_steps,
            z_threshold=cfg.obs.recorder_z, warmup=cfg.obs.recorder_warmup,
            max_bundles=cfg.obs.recorder_max_bundles)
        log.info("flight recorder armed: bundles -> %s/postmortem", rec_dir)

    if cfg.trainer.pipeline_depth > 0:
        # pipelined rollout (ARCHITECTURE.md "Pipeline overlap" +
        # "Bounded-staleness async training"): announce the mode +
        # staleness handling up front, since the step records will look
        # different (perf/pipeline_* + perf/staleness_* keys, async
        # weight pushes that may overlap generation at staleness_limit>1)
        log.info(
            "pipelined rollout enabled: depth=%d, staleness_limit=%d "
            "(%s), stale-rollout IS correction=%s (cap=%.2f)",
            cfg.trainer.pipeline_depth, cfg.trainer.staleness_limit,
            "hard wait_pushed fence" if cfg.trainer.staleness_limit <= 1
            else "bounded-staleness admission gate",
            "on" if cfg.trainer.rollout_is_correction else "OFF",
            cfg.trainer.rollout_is_cap)

    # training health plane (obs/rlhealth.py): default ON — training/*
    # step metrics, /statusz training section, training.json bundles.
    # obs.rlhealth=false turns it off (health=False disables the ledger).
    if cfg.obs.rlhealth:
        from polyrl_tpu.obs.rlhealth import TrainingHealthLedger

        health = TrainingHealthLedger(
            tail_steps=cfg.obs.rlhealth_tail,
            max_group_rows=cfg.obs.rlhealth_group_rows)
    else:
        health = False

    # closed-loop autoscaling (rollout/autoscale.py): default OFF — when
    # enabled (and a PoolManager exists to act on), the controller ticks
    # once per step from the fit loop; a spot-market trace doubles as its
    # CapacityProvider so scripted offers satisfy its add requests
    autoscale = None
    if (cfg.rollout.autoscale.enabled
            and getattr(rollout, "pool", None) is not None):
        from polyrl_tpu.rollout.autoscale import AutoscaleController

        capacity = None
        if cfg.rollout.spot_market.enabled:
            from polyrl_tpu.rollout.spotmarket import SpotMarket

            market = SpotMarket(
                rollout.pool, cfg.rollout.spot_market,
                injector=getattr(rollout, "fault_injector", None))
            market.start()
            cleanup.append(market.stop)
            capacity = market
        autoscale = AutoscaleController(
            rollout.pool, rollout.balance, cfg.rollout.autoscale,
            capacity=capacity, rollout=rollout)
        cleanup.append(autoscale.close)
        log.info("autoscale controller armed: envelope [%d, %d]%s",
                 cfg.rollout.autoscale.min_engines,
                 cfg.rollout.autoscale.max_engines,
                 " (dry-run)" if cfg.rollout.autoscale.dry_run else "")

    val_dataset = build_dataset(cfg, "val")
    trainer = StreamRLTrainer(
        cfg.trainer, actor, rollout, tokenizer, reward_manager, loader,
        critic=critic, ref_policy=ref_policy, logger=logger,
        val_dataset=val_dataset, recorder=recorder, health=health,
        autoscale=autoscale)
    if cfg.obs.statusz and multihost.is_main():
        # live health plane: GET /statusz answers "what is this trainer
        # doing right now" (shared schema with the rollout server's route)
        srv = trainer.start_statusz(port=cfg.obs.statusz_port,
                                    host=cfg.obs.statusz_host)
        cleanup.append(trainer.stop_statusz)
        log.info("trainer /statusz serving at http://%s/statusz",
                 srv.endpoint)
    return trainer


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m polyrl_tpu.train",
        description="Streaming PPO/GRPO trainer (colocated or disaggregated)")
    parser.add_argument("--config", default=None, help="YAML run config")
    parser.add_argument("--print-config", action="store_true",
                        help="resolve config, print as YAML, exit")
    parser.add_argument("overrides", nargs="*",
                        help="dotted overrides: trainer.total_steps=100 ...")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    # multi-host bring-up first (no-op single-process): jax.distributed from
    # the standard env vars, before any backend use (parallel/distributed.py)
    from polyrl_tpu.parallel import distributed

    distributed.initialize()
    cfg = load_config(args.config, args.overrides)
    if args.print_config:
        import yaml

        print(yaml.safe_dump(to_dict(cfg), sort_keys=False))
        return 0

    cleanup: list = []
    try:
        trainer = build_trainer(cfg, cleanup)
        if trainer._recorder is not None:
            # SIGTERM (driver timeout, preemption) dumps a post-mortem
            # bundle before the process dies — main-thread entry only
            trainer._recorder.install_signal_handlers()
        history = trainer.fit()
        if history:
            last = history[-1]
            log.info("finished %d steps; final metrics: %s",
                     trainer.global_step,
                     {k: round(v, 5) for k, v in sorted(last.items())})
        return 0
    finally:
        for fn in reversed(cleanup):
            try:
                fn()
            except Exception:  # noqa: BLE001
                log.exception("cleanup failed")


if __name__ == "__main__":
    sys.exit(main())
