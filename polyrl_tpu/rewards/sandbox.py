"""Remote sandbox-service code execution for pod-scale code RL.

TPU-pod equivalent of the reference's sandbox-fusion reward path
(``rlboost/verl_stream/trainer/ppo/reward.py:95-150``: a shared service URL
plus a concurrency semaphore handed into ``default_compute_score``). One
training host scoring a stream batch can need hundreds of code executions
per reward call; a single VM's local subprocess sandbox
(``scorers._run_sandboxed``) serializes on its own cores, while a sandbox
service horizontally scales the untrusted execution AND keeps it off the
training hosts.

Design differences from the reference (TPU-first redesign, not a port):

- threads + ``threading.Semaphore`` instead of a multiprocessing.Manager
  semaphore — the reward managers here score with thread pools
  (``manager.py``), not Ray actor processes, so process-shared state is
  unnecessary.
- graceful degradation is built in: any service failure (connect error,
  HTTP 5xx, malformed body) falls back to the local rlimit'd sandbox for
  that one run (bounded by ``fallback_local``), so reward computation
  survives a sandbox outage instead of zeroing a training batch.

Protocol: POST ``{url}/run_code`` with
``{"code", "language": "python", "stdin", "run_timeout", "memory_limit_MB"}``
returning ``{"status": "Success", "run_result": {"return_code": 0,
"stdout": ..., "stderr": ...}}`` — the sandbox-fusion wire shape the
reference's scorer speaks.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request

from polyrl_tpu.rewards.scorers import _run_sandboxed, default_compute_score

log = logging.getLogger(__name__)


class SandboxClient:
    """Bounded-concurrency client for a remote code-execution service.

    ``run()`` matches the ``run_fn(code, stdin, timeout_s) -> (ok, stdout)``
    seam in ``scorers.compute_score_code``, so a client instance plugs
    straight into the scoring dispatch.
    """

    def __init__(
        self,
        url: str,
        max_concurrent: int = 64,
        timeout_s: float = 30.0,
        memory_limit_mb: int = 1024,
        fallback_local: bool = True,
    ):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s
        self.memory_limit_mb = memory_limit_mb
        self.fallback_local = fallback_local
        # the semaphore bounds in-flight requests ACROSS reward-manager
        # worker threads (reference: max_concurrent=64, reward.py:137)
        self._sem = threading.Semaphore(max_concurrent)
        self._lock = threading.Lock()
        self.remote_runs = 0
        self.remote_failures = 0
        self.local_fallbacks = 0

    # -- execution ----------------------------------------------------------

    def run(self, code: str, stdin: str = "",
            timeout_s: float | None = None) -> tuple[bool, str]:
        """Execute ``code`` remotely; (ok, stdout). Service failure (NOT a
        failing program — that's a real score of 0) falls back locally."""
        t = timeout_s if timeout_s is not None else self.timeout_s
        payload = json.dumps({
            "code": code,
            "language": "python",
            "stdin": stdin,
            "run_timeout": t,
            "memory_limit_MB": self.memory_limit_mb,
        }).encode()
        req = urllib.request.Request(
            self.url + "/run_code", data=payload, method="POST",
            headers={"Content-Type": "application/json"})
        with self._sem:
            try:
                # service-side run_timeout plus headroom for queueing
                with urllib.request.urlopen(req, timeout=t + 10.0) as r:
                    body = json.loads(r.read())
                with self._lock:
                    self.remote_runs += 1
            except (urllib.error.URLError, OSError, ValueError,
                    TimeoutError) as exc:
                with self._lock:
                    self.remote_failures += 1
                    self.local_fallbacks += self.fallback_local
                log.warning("sandbox service error (%s): %s", self.url, exc)
                if self.fallback_local:
                    return _run_sandboxed(code, stdin, t)
                return False, f"sandbox service error: {exc}"
        run = body.get("run_result") or {}
        status = body.get("status", "")
        if status and status != "Success":
            # SandboxError / compile failure: treat like a non-zero exit
            return False, str(body.get("message", status))[:500]
        ok = run.get("return_code", 1) == 0 and run.get("status", "Finished") \
            in ("Finished", "Success")
        return ok, str(run.get("stdout", ""))

    # -- scoring ------------------------------------------------------------

    def compute_score(self, data_source: str, solution_str: str,
                      ground_truth: str, extra_info: dict | None = None
                      ) -> float:
        """Drop-in ``compute_score`` with code execution routed here
        (what the reference builds with functools.partial,
        reward.py:138-143)."""
        return default_compute_score(data_source, solution_str, ground_truth,
                                     extra_info, run_fn=self.run)

    def stats(self) -> dict:
        with self._lock:
            return {"remote_runs": self.remote_runs,
                    "remote_failures": self.remote_failures,
                    "local_fallbacks": self.local_fallbacks}
