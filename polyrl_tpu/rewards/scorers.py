"""Rule-based per-dataset reward scorers.

TPU-neutral (pure Python/CPU) equivalent of the reference's
``default_compute_score`` dispatch (reference
``rlboost/verl_stream/utils/reward_score/__init__.py:19-117``): per
``data_source`` routing to gsm8k / MATH-style / code scorers. Scores are
computed on the driver host while the TPUs run the next ibatch — same
overlap the reference gets from async Ray reward tasks
(``reward.py:153-190``).
"""

from __future__ import annotations

import re


def extract_gsm8k_answer(text: str, method: str = "strict") -> str | None:
    """GSM8K: final number after '####' (strict) or last number (flexible)."""
    if method == "strict":
        m = re.search(r"####\s*(-?[0-9.,]+)", text)
        if m is None:
            return None
        return m.group(1).replace(",", "").rstrip(".")
    nums = re.findall(r"-?[0-9][0-9.,]*", text)
    if not nums:
        return None
    return nums[-1].replace(",", "").rstrip(".")


def _num_eq(a: str, b: str) -> bool:
    try:
        return abs(float(a) - float(b)) < 1e-6
    except (TypeError, ValueError):
        return a == b


def compute_score_gsm8k(
    solution_str: str,
    ground_truth: str,
    method: str = "flexible",
    correct_score: float = 1.0,
    format_score: float = 0.0,
) -> float:
    answer = extract_gsm8k_answer(solution_str, method)
    if answer is None:
        return 0.0
    return correct_score if _num_eq(answer, ground_truth) else format_score


_BOXED_RE = re.compile(r"\\boxed\{")


def extract_boxed_answer(text: str) -> str | None:
    """Last \\boxed{...} with balanced braces (MATH-style)."""
    starts = [m.end() for m in _BOXED_RE.finditer(text)]
    if not starts:
        return None
    start = starts[-1]
    depth = 1
    for i in range(start, len(text)):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return text[start:i]
    return None


def _normalize_math(ans: str) -> str:
    ans = ans.strip()
    ans = ans.replace("\\left", "").replace("\\right", "")
    ans = ans.replace("\\!", "").replace("\\,", "").replace("\\;", "").replace(" ", "")
    ans = ans.replace("\\%", "").replace("%", "")
    ans = ans.replace("\\$", "").replace("$", "")
    ans = re.sub(r"\\text\{[^}]*\}", "", ans)
    ans = re.sub(r"\\mbox\{[^}]*\}", "", ans)
    ans = ans.replace("\\dfrac", "\\frac").replace("\\tfrac", "\\frac")
    ans = ans.rstrip(".")
    # \frac{a}{b} → a/b for simple numeric fractions
    m = re.fullmatch(r"\\frac\{(-?\d+)\}\{(-?\d+)\}", ans)
    if m:
        ans = f"{m.group(1)}/{m.group(2)}"
    if ans.endswith("\\"):
        ans = ans[:-1]
    return ans


def compute_score_math(solution_str: str, ground_truth: str) -> float:
    answer = extract_boxed_answer(solution_str)
    if answer is None:
        return 0.0
    a, b = _normalize_math(answer), _normalize_math(ground_truth)
    if a == b or _num_eq(a, b):
        return 1.0
    # numeric fraction equivalence
    def to_float(s: str) -> float | None:
        m = re.fullmatch(r"(-?\d+(?:\.\d+)?)/(-?\d+(?:\.\d+)?)", s)
        if m:
            try:
                return float(m.group(1)) / float(m.group(2))
            except ZeroDivisionError:
                return None
        try:
            return float(s)
        except ValueError:
            return None
    fa, fb = to_float(a), to_float(b)
    if fa is not None and fb is not None:
        return 1.0 if abs(fa - fb) < 1e-6 else 0.0
    return 0.0


_GEO3K_FORMAT_RE = re.compile(r"<think>.*</think>.*\\boxed\{.*\}.*",
                              re.DOTALL)


def compute_score_geo3k(solution_str: str, ground_truth: str) -> float:
    """Geometry3k (reference dispatch row reward_score/__init__.py:92-95 →
    verl's geo3k scorer): 0.9 × boxed-answer accuracy + 0.1 × format reward
    (a full ``<think>…</think> … \\boxed{}`` trace). The accuracy half
    reuses the boxed-math equivalence grader; the multimodal (image) input
    side rides the normal prompt path — scoring is text-only, as in the
    reference."""
    acc = compute_score_math(solution_str, ground_truth)
    fmt = 1.0 if _GEO3K_FORMAT_RE.fullmatch(solution_str) else 0.0
    return 0.9 * acc + 0.1 * fmt


def compute_score_math_dapo(
    solution_str: str,
    ground_truth: str,
    correct_score: float = 1.0,
    incorrect_score: float = -1.0,
) -> float:
    """DAPO/AIME-style strict scoring: the answer must appear in a
    ``\\boxed{}``; correct → +1, anything else → −1 (the reference's
    math_dapo scorer's ±1 scheme, reward_score/__init__.py dispatch row
    math_dapo/aime)."""
    answer = extract_boxed_answer(solution_str)
    if answer is None:
        return incorrect_score
    ok = compute_score_math(f"\\boxed{{{answer}}}", ground_truth) > 0.0
    return correct_score if ok else incorrect_score


_ANSWER_PATTERNS = (
    re.compile(r"(?:final answer|answer)\s*(?:is|:)\s*([^\n.,;]+)", re.IGNORECASE),
)


def compute_score_prime_math(solution_str: str, ground_truth: str) -> float:
    """Robust math equivalence with fallback extraction (the reference's
    numina → prime_math route): boxed first, then 'answer is X' phrasing,
    then last number."""
    if compute_score_math(solution_str, ground_truth) > 0.0:
        return 1.0
    gt = _normalize_math(ground_truth)
    for pat in _ANSWER_PATTERNS:
        matches = pat.findall(solution_str)
        if matches and (_normalize_math(matches[-1]) == gt
                        or _num_eq(_normalize_math(matches[-1]), gt)):
            return 1.0
    last = extract_gsm8k_answer(solution_str, method="flexible")
    if last is not None and _num_eq(last, gt):
        return 1.0
    return 0.0


# -- code execution (local sandbox) -----------------------------------------

_CODE_BLOCK_RE = re.compile(r"```(?:python|py)?\s*\n(.*?)```", re.DOTALL)


def extract_code(solution_str: str) -> str | None:
    """Last fenced code block, else None."""
    blocks = _CODE_BLOCK_RE.findall(solution_str)
    return blocks[-1].strip() if blocks else None


def _run_sandboxed(code: str, stdin: str, timeout_s: float) -> tuple[bool, str]:
    """Run model-emitted code in an isolated python subprocess with CPU and
    memory rlimits — the local stand-in for the reference's sandbox-fusion
    code-execution service (reward.py:95-150)."""
    import resource
    import subprocess
    import sys

    def limits():
        resource.setrlimit(resource.RLIMIT_CPU, (int(timeout_s) + 1,) * 2)
        resource.setrlimit(resource.RLIMIT_AS, (1 << 30,) * 2)
        resource.setrlimit(resource.RLIMIT_NPROC, (64, 64))

    try:
        proc = subprocess.run(
            [sys.executable, "-I", "-c", code], input=stdin,
            capture_output=True, text=True, timeout=timeout_s,
            preexec_fn=limits)
    except subprocess.TimeoutExpired:
        return False, "timeout"
    except Exception as exc:  # noqa: BLE001
        return False, str(exc)
    if proc.returncode != 0:
        return False, proc.stderr[-500:]
    return True, proc.stdout


def compute_score_code(
    solution_str: str,
    ground_truth: str,
    extra_info: dict | None = None,
    timeout_s: float = 6.0,
    run_fn=None,
) -> float:
    """Code-contest scoring: fraction of test cases passed (the reference's
    prime_code / sandbox path for codecontests/apps/codeforces/taco).

    Test cases come from ``extra_info`` (or JSON-decoded ``ground_truth``):
    ``{"inputs": [...], "outputs": [...]}`` stdin/stdout pairs, or
    ``{"asserts": "..."}`` appended to the program.

    ``run_fn(code, stdin, timeout_s) -> (ok, stdout)`` selects the execution
    backend: default is the local rlimit'd subprocess; the remote
    sandbox-service client (rewards/sandbox.py) plugs in here for pod-scale
    scoring.
    """
    if run_fn is None:
        run_fn = _run_sandboxed
    code = extract_code(solution_str)
    if code is None:
        return 0.0
    tests = None
    if extra_info and isinstance(extra_info.get("test_cases"), dict):
        tests = extra_info["test_cases"]
    else:
        import json as _json

        try:
            parsed = _json.loads(ground_truth)
            if isinstance(parsed, dict):
                tests = parsed
        except (ValueError, TypeError):
            tests = None
    if not tests:
        return 0.0
    if "asserts" in tests:
        ok, _ = run_fn(code + "\n\n" + tests["asserts"], "", timeout_s)
        return 1.0 if ok else 0.0
    inputs = tests.get("inputs", [])
    outputs = tests.get("outputs", [])
    if not inputs:
        return 0.0
    passed = 0
    for stdin, expect in zip(inputs, outputs):
        ok, out = run_fn(code, str(stdin), timeout_s)
        if ok and out.strip() == str(expect).strip():
            passed += 1
    return passed / len(inputs)


# -- QA exact match ---------------------------------------------------------

_ARTICLES_RE = re.compile(r"\b(a|an|the)\b")
_PUNCT_RE = re.compile(r"[^\w\s]")


def _normalize_qa(text: str) -> str:
    text = text.lower()
    text = _PUNCT_RE.sub(" ", text)
    text = _ARTICLES_RE.sub(" ", text)
    return " ".join(text.split())


def compute_score_qa_em(
    solution_str: str,
    ground_truth: str,
    extra_info: dict | None = None,
) -> float:
    """SearchR1-style QA exact match (reference searchR1 QA-EM row):
    normalized answer (inside <answer></answer> tags when present, else the
    full response tail) must equal one of the gold answers
    ('|||'-separated)."""
    m = re.findall(r"<answer>(.*?)</answer>", solution_str, re.DOTALL)
    cand = m[-1] if m else solution_str
    cand_n = _normalize_qa(cand)
    golds = [g for g in (ground_truth or "").split("|||")]
    for g in golds:
        gn = _normalize_qa(g)
        if gn and (cand_n == gn or (m and gn in cand_n)):
            return 1.0
    return 0.0


def default_compute_score(
    data_source: str,
    solution_str: str,
    ground_truth: str,
    extra_info: dict | None = None,
    run_fn=None,
) -> float:
    """Per-dataset dispatch (reference reward_score/__init__.py:19-117).
    ``run_fn`` overrides the code-execution backend (rewards/sandbox.py)."""
    ds = (data_source or "").lower()
    if "gsm8k" in ds:
        return compute_score_gsm8k(solution_str, ground_truth)
    if any(k in ds for k in ("math_dapo", "aime", "dapo")):
        return compute_score_math_dapo(solution_str, ground_truth)
    if any(k in ds for k in ("numina", "prime_math")):
        return compute_score_prime_math(solution_str, ground_truth)
    if any(k in ds for k in ("geometry3k", "geo3k")):
        return compute_score_geo3k(solution_str, ground_truth)
    if any(k in ds for k in ("math", "openr1", "deepscaler")):
        return compute_score_math(solution_str, ground_truth)
    if any(k in ds for k in ("code", "apps", "taco", "codeforces")):
        return compute_score_code(solution_str, ground_truth, extra_info,
                                  run_fn=run_fn)
    if any(k in ds for k in ("searchr1", "nq", "triviaqa", "hotpotqa", "qa_em")):
        return compute_score_qa_em(solution_str, ground_truth, extra_info)
    # default: MATH-style then gsm8k-style
    score = compute_score_math(solution_str, ground_truth)
    if score == 0.0:
        score = compute_score_gsm8k(solution_str, ground_truth)
    return score
