"""Rule-based per-dataset reward scorers.

TPU-neutral (pure Python/CPU) equivalent of the reference's
``default_compute_score`` dispatch (reference
``rlboost/verl_stream/utils/reward_score/__init__.py:19-117``): per
``data_source`` routing to gsm8k / MATH-style / code scorers. Scores are
computed on the driver host while the TPUs run the next ibatch — same
overlap the reference gets from async Ray reward tasks
(``reward.py:153-190``).
"""

from __future__ import annotations

import re


def extract_gsm8k_answer(text: str, method: str = "strict") -> str | None:
    """GSM8K: final number after '####' (strict) or last number (flexible)."""
    if method == "strict":
        m = re.search(r"####\s*(-?[0-9.,]+)", text)
        if m is None:
            return None
        return m.group(1).replace(",", "").rstrip(".")
    nums = re.findall(r"-?[0-9][0-9.,]*", text)
    if not nums:
        return None
    return nums[-1].replace(",", "").rstrip(".")


def _num_eq(a: str, b: str) -> bool:
    try:
        return abs(float(a) - float(b)) < 1e-6
    except (TypeError, ValueError):
        return a == b


def compute_score_gsm8k(
    solution_str: str,
    ground_truth: str,
    method: str = "flexible",
    correct_score: float = 1.0,
    format_score: float = 0.0,
) -> float:
    answer = extract_gsm8k_answer(solution_str, method)
    if answer is None:
        return 0.0
    return correct_score if _num_eq(answer, ground_truth) else format_score


_BOXED_RE = re.compile(r"\\boxed\{")


def extract_boxed_answer(text: str) -> str | None:
    """Last \\boxed{...} with balanced braces (MATH-style)."""
    starts = [m.end() for m in _BOXED_RE.finditer(text)]
    if not starts:
        return None
    start = starts[-1]
    depth = 1
    for i in range(start, len(text)):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return text[start:i]
    return None


def _normalize_math(ans: str) -> str:
    ans = ans.strip()
    ans = ans.replace("\\left", "").replace("\\right", "")
    ans = ans.replace("\\!", "").replace("\\,", "").replace("\\;", "").replace(" ", "")
    ans = ans.replace("\\%", "").replace("%", "")
    ans = ans.replace("\\$", "").replace("$", "")
    ans = re.sub(r"\\text\{[^}]*\}", "", ans)
    ans = re.sub(r"\\mbox\{[^}]*\}", "", ans)
    ans = ans.replace("\\dfrac", "\\frac").replace("\\tfrac", "\\frac")
    ans = ans.rstrip(".")
    # \frac{a}{b} → a/b for simple numeric fractions
    m = re.fullmatch(r"\\frac\{(-?\d+)\}\{(-?\d+)\}", ans)
    if m:
        ans = f"{m.group(1)}/{m.group(2)}"
    if ans.endswith("\\"):
        ans = ans[:-1]
    return ans


def compute_score_math(solution_str: str, ground_truth: str) -> float:
    answer = extract_boxed_answer(solution_str)
    if answer is None:
        return 0.0
    a, b = _normalize_math(answer), _normalize_math(ground_truth)
    if a == b or _num_eq(a, b):
        return 1.0
    # numeric fraction equivalence
    def to_float(s: str) -> float | None:
        m = re.fullmatch(r"(-?\d+(?:\.\d+)?)/(-?\d+(?:\.\d+)?)", s)
        if m:
            try:
                return float(m.group(1)) / float(m.group(2))
            except ZeroDivisionError:
                return None
        try:
            return float(s)
        except ValueError:
            return None
    fa, fb = to_float(a), to_float(b)
    if fa is not None and fb is not None:
        return 1.0 if abs(fa - fb) < 1e-6 else 0.0
    return 0.0


def default_compute_score(
    data_source: str,
    solution_str: str,
    ground_truth: str,
    extra_info: dict | None = None,
) -> float:
    """Per-dataset dispatch (reference reward_score/__init__.py:19-117)."""
    ds = (data_source or "").lower()
    if "gsm8k" in ds:
        return compute_score_gsm8k(solution_str, ground_truth)
    if any(k in ds for k in ("math", "aime", "openr1", "deepscaler", "numina", "dapo")):
        return compute_score_math(solution_str, ground_truth)
    if any(k in ds for k in ("code", "apps", "taco", "codeforces")):
        # sandboxed code execution scoring is gated off in this environment
        # (reference uses sandbox-fusion, reward.py:95-150); fall back to
        # exact-match of extracted answer.
        return 1.0 if ground_truth.strip() and ground_truth.strip() in solution_str else 0.0
    # default: MATH-style then gsm8k-style
    score = compute_score_math(solution_str, ground_truth)
    if score == 0.0:
        score = compute_score_gsm8k(solution_str, ground_truth)
    return score
