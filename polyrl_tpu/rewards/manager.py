"""Reward managers: turn generated token batches into token-level scores.

Equivalent of the reference's reward layer C17 (``load_reward_manager`` over
naive/prime/batch/dapo managers + custom fn, reference
``rlboost/verl_stream/trainer/ppo/reward.py:95-190``). The naive manager
decodes responses, calls the per-dataset scorer, and places the scalar
outcome reward on the LAST response token (outcome supervision); token-level
shaping hooks are the manager's job.
"""

from __future__ import annotations

import concurrent.futures
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable

import numpy as np

from polyrl_tpu.data.batch import TensorBatch
from polyrl_tpu.rewards.scorers import default_compute_score


@dataclass
class RewardResult:
    token_level_scores: np.ndarray  # [B, T_resp] f32
    scores: np.ndarray              # [B] sequence-level
    metrics: dict


class NaiveRewardManager:
    """Decode → score → scatter to last response token."""

    def __init__(
        self,
        tokenizer,
        compute_score: Callable = default_compute_score,
        num_workers: int = 4,
    ):
        self.tokenizer = tokenizer
        self.compute_score = compute_score
        self.num_workers = num_workers

    def __call__(self, batch: TensorBatch) -> RewardResult:
        responses = np.asarray(batch["responses"])          # [B, T]
        response_mask = np.asarray(batch["response_mask"])  # [B, T]
        ground_truth = batch["ground_truth"]                # non-tensor [B]
        data_sources = (
            batch["data_source"] if "data_source" in batch
            else np.array(["gsm8k"] * len(responses), dtype=object)
        )

        extras = (batch["extra_info"] if "extra_info" in batch
                  else [None] * len(responses))
        lengths = response_mask.sum(axis=-1).astype(np.int64)
        texts = self.tokenizer.batch_decode(
            [responses[i, : lengths[i]] for i in range(len(responses))],
            skip_special_tokens=True,
        )

        def score_one(i: int) -> float:
            return float(
                self.compute_score(str(data_sources[i]), texts[i],
                                   str(ground_truth[i]), extras[i])
            )

        if self.num_workers > 1 and len(texts) > 1:
            with concurrent.futures.ThreadPoolExecutor(self.num_workers) as ex:
                scores = np.fromiter(ex.map(score_one, range(len(texts))), dtype=np.float32)
        else:
            scores = np.array([score_one(i) for i in range(len(texts))], dtype=np.float32)

        token_scores = np.zeros_like(response_mask, dtype=np.float32)
        for i, ln in enumerate(lengths):
            if ln > 0:
                token_scores[i, ln - 1] = scores[i]
        return RewardResult(
            token_level_scores=token_scores,
            scores=scores,
            metrics={"reward/mean": float(scores.mean()) if len(scores) else 0.0,
                     "reward/max": float(scores.max()) if len(scores) else 0.0,
                     "reward/min": float(scores.min()) if len(scores) else 0.0},
        )


class BatchRewardManager(NaiveRewardManager):
    """Scores the whole batch with ONE call — ``compute_score`` receives
    parallel lists and returns a list of floats (the reference's batch
    reward manager shape, for vectorized or service-backed scorers)."""

    def _score_batch(self, data_sources, texts, ground_truth, extras) -> np.ndarray:
        out = self.compute_score(
            [str(d) for d in data_sources], list(texts),
            [str(g) for g in ground_truth], list(extras))
        return np.asarray(out, dtype=np.float32)

    def __call__(self, batch: TensorBatch) -> RewardResult:
        responses = np.asarray(batch["responses"])
        response_mask = np.asarray(batch["response_mask"])
        ground_truth = batch["ground_truth"]
        data_sources = (batch["data_source"] if "data_source" in batch
                        else np.array([""] * len(responses), dtype=object))
        extras = (batch["extra_info"] if "extra_info" in batch
                  else [None] * len(responses))
        lengths = response_mask.sum(axis=-1).astype(np.int64)
        texts = self.tokenizer.batch_decode(
            [responses[i, : lengths[i]] for i in range(len(responses))],
            skip_special_tokens=True)
        scores = self._score_batch(data_sources, texts, ground_truth, extras)
        token_scores = np.zeros_like(response_mask, dtype=np.float32)
        for i, ln in enumerate(lengths):
            if ln > 0:
                token_scores[i, ln - 1] = scores[i]
        return RewardResult(
            token_level_scores=token_scores, scores=scores,
            metrics={"reward/mean": float(scores.mean()) if len(scores) else 0.0,
                     "reward/max": float(scores.max()) if len(scores) else 0.0,
                     "reward/min": float(scores.min()) if len(scores) else 0.0})


class DAPORewardManager(NaiveRewardManager):
    """Naive scoring + DAPO overlong soft penalty: responses inside the
    last ``overlong_buffer_len`` tokens before ``max_response_length`` get a
    linearly increasing penalty up to ``-penalty_factor`` (the reference's
    dapo manager; pairs with the ±1 math_dapo scorer)."""

    def __init__(self, tokenizer, compute_score=None, num_workers: int = 4,
                 max_response_length: int = 0, overlong_buffer_len: int = 0,
                 penalty_factor: float = 1.0):
        super().__init__(tokenizer, compute_score or default_compute_score,
                         num_workers)
        self.max_response_length = max_response_length
        self.overlong_buffer_len = overlong_buffer_len
        self.penalty_factor = penalty_factor

    def __call__(self, batch: TensorBatch) -> RewardResult:
        out = super().__call__(batch)
        if not (self.max_response_length and self.overlong_buffer_len):
            return out
        response_mask = np.asarray(batch["response_mask"])
        lengths = response_mask.sum(axis=-1).astype(np.int64)
        expected = self.max_response_length - self.overlong_buffer_len
        over = np.clip(lengths - expected, 0, self.overlong_buffer_len)
        penalty = -(over / self.overlong_buffer_len) * self.penalty_factor
        for i, ln in enumerate(lengths):
            if ln > 0 and penalty[i] < 0.0:
                out.token_level_scores[i, ln - 1] += penalty[i]
                out.scores[i] += penalty[i]
        out.metrics["reward/overlong_penalty_mean"] = float(penalty.mean())
        return out


class PrimeRewardManager(NaiveRewardManager):
    """Parallel scoring with per-sample timeout and zero-on-error — for
    slow/flaky scorers (code execution services; the reference's prime
    manager wraps sandbox-fusion with a semaphore, reward.py:95-150)."""

    def __init__(self, tokenizer, compute_score=None, num_workers: int = 8,
                 timeout_s: float = 30.0):
        super().__init__(tokenizer, compute_score or default_compute_score,
                         num_workers)
        self.timeout_s = timeout_s

    def __call__(self, batch: TensorBatch) -> RewardResult:
        responses = np.asarray(batch["responses"])
        response_mask = np.asarray(batch["response_mask"])
        ground_truth = batch["ground_truth"]
        data_sources = (batch["data_source"] if "data_source" in batch
                        else np.array([""] * len(responses), dtype=object))
        extras = (batch["extra_info"] if "extra_info" in batch
                  else [None] * len(responses))
        lengths = response_mask.sum(axis=-1).astype(np.int64)
        texts = self.tokenizer.batch_decode(
            [responses[i, : lengths[i]] for i in range(len(responses))],
            skip_special_tokens=True)

        def score_one(i: int) -> float:
            return float(self.compute_score(
                str(data_sources[i]), texts[i], str(ground_truth[i]), extras[i]))

        scores = np.zeros(len(texts), dtype=np.float32)
        n_err = 0
        # daemon worker threads, NOT ThreadPoolExecutor: executor workers
        # are non-daemon and joined by an atexit hook, so a permanently
        # wedged scorer would block interpreter shutdown; daemon threads are
        # truly abandonable. Overall deadline = timeout_s per wave.
        n = len(texts)
        work: "queue.Queue[int]" = queue.Queue()
        for i in range(n):
            work.put(i)
        done: "queue.Queue[tuple[int, float | None]]" = queue.Queue()

        def _worker() -> None:
            while True:
                try:
                    i = work.get_nowait()
                except queue.Empty:
                    return
                try:
                    done.put((i, score_one(i)))
                except Exception:  # noqa: BLE001 — scorer crash
                    done.put((i, None))

        for _ in range(min(self.num_workers, max(n, 1))):
            threading.Thread(target=_worker, daemon=True).start()
        n_waves = max(1, -(-n // self.num_workers))
        deadline = time.monotonic() + self.timeout_s * n_waves
        collected = 0
        got = np.zeros(n, dtype=bool)
        while collected < n:
            try:
                i, s = done.get(timeout=max(0.0, deadline - time.monotonic()))
            except queue.Empty:
                break  # deadline: drain what already finished, then give up
            got[i] = True
            collected += 1
            if s is None:
                n_err += 1
            else:
                scores[i] = s
        # drain results that landed right at the deadline (no busy wait)
        while True:
            try:
                i, s = done.get_nowait()
            except queue.Empty:
                break
            got[i] = True
            if s is not None:
                scores[i] = s
            else:
                n_err += 1
        n_err += int((~got).sum())  # abandoned (hung/unstarted) samples
        token_scores = np.zeros_like(response_mask, dtype=np.float32)
        for i, ln in enumerate(lengths):
            if ln > 0:
                token_scores[i, ln - 1] = scores[i]
        return RewardResult(
            token_level_scores=token_scores, scores=scores,
            metrics={"reward/mean": float(scores.mean()) if len(scores) else 0.0,
                     "reward/max": float(scores.max()) if len(scores) else 0.0,
                     "reward/min": float(scores.min()) if len(scores) else 0.0,
                     "reward/score_errors": float(n_err)})


def compute_reward_async(manager, batch: TensorBatch):
    """Run the manager off-thread; returns a Future (the reference's Ray
    compute_reward_async, reward.py:153-190 — reward overlaps the next
    ibatch's device work)."""
    ex = concurrent.futures.ThreadPoolExecutor(1)
    fut = ex.submit(manager, batch)
    ex.shutdown(wait=False)
    return fut


REWARD_MANAGERS = {
    "naive": NaiveRewardManager,
    "batch": BatchRewardManager,
    "dapo": DAPORewardManager,
    "prime": PrimeRewardManager,
}


def load_reward_manager(name: str, tokenizer, compute_score=None, **kw):
    """Resolve a reward manager by name (reference reward.py:95-150)."""
    cls = REWARD_MANAGERS[name]
    return cls(tokenizer, compute_score=compute_score or default_compute_score, **kw)
