"""Reward managers: turn generated token batches into token-level scores.

Equivalent of the reference's reward layer C17 (``load_reward_manager`` over
naive/prime/batch/dapo managers + custom fn, reference
``rlboost/verl_stream/trainer/ppo/reward.py:95-190``). The naive manager
decodes responses, calls the per-dataset scorer, and places the scalar
outcome reward on the LAST response token (outcome supervision); token-level
shaping hooks are the manager's job.
"""

from __future__ import annotations

import concurrent.futures
from dataclasses import dataclass
from typing import Callable

import numpy as np

from polyrl_tpu.data.batch import TensorBatch
from polyrl_tpu.rewards.scorers import default_compute_score


@dataclass
class RewardResult:
    token_level_scores: np.ndarray  # [B, T_resp] f32
    scores: np.ndarray              # [B] sequence-level
    metrics: dict


class NaiveRewardManager:
    """Decode → score → scatter to last response token."""

    def __init__(
        self,
        tokenizer,
        compute_score: Callable = default_compute_score,
        num_workers: int = 4,
    ):
        self.tokenizer = tokenizer
        self.compute_score = compute_score
        self.num_workers = num_workers

    def __call__(self, batch: TensorBatch) -> RewardResult:
        responses = np.asarray(batch["responses"])          # [B, T]
        response_mask = np.asarray(batch["response_mask"])  # [B, T]
        ground_truth = batch["ground_truth"]                # non-tensor [B]
        data_sources = (
            batch["data_source"] if "data_source" in batch
            else np.array(["gsm8k"] * len(responses), dtype=object)
        )

        lengths = response_mask.sum(axis=-1).astype(np.int64)
        texts = self.tokenizer.batch_decode(
            [responses[i, : lengths[i]] for i in range(len(responses))],
            skip_special_tokens=True,
        )

        def score_one(i: int) -> float:
            return float(
                self.compute_score(str(data_sources[i]), texts[i], str(ground_truth[i]))
            )

        if self.num_workers > 1 and len(texts) > 1:
            with concurrent.futures.ThreadPoolExecutor(self.num_workers) as ex:
                scores = np.fromiter(ex.map(score_one, range(len(texts))), dtype=np.float32)
        else:
            scores = np.array([score_one(i) for i in range(len(texts))], dtype=np.float32)

        token_scores = np.zeros_like(response_mask, dtype=np.float32)
        for i, ln in enumerate(lengths):
            if ln > 0:
                token_scores[i, ln - 1] = scores[i]
        return RewardResult(
            token_level_scores=token_scores,
            scores=scores,
            metrics={"reward/mean": float(scores.mean()) if len(scores) else 0.0,
                     "reward/max": float(scores.max()) if len(scores) else 0.0,
                     "reward/min": float(scores.min()) if len(scores) else 0.0},
        )


REWARD_MANAGERS = {"naive": NaiveRewardManager}


def load_reward_manager(name: str, tokenizer, compute_score=None, **kw):
    """Resolve a reward manager by name (reference reward.py:95-150)."""
    cls = REWARD_MANAGERS[name]
    return cls(tokenizer, compute_score=compute_score or default_compute_score, **kw)
