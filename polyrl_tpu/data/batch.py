"""TensorBatch — JAX-native batch container.

The reference leans on verl's ``DataProto`` (torch TensorDict + numpy
non-tensor batch + meta_info) for every trainer⇄worker exchange (SURVEY.md
§2.5; used at reference ``stream_ray_trainer.py:363,456-463,508,582``).
TensorBatch is the TPU-native equivalent: a pytree-registered container of

- ``tensors``: dict[str, jnp.ndarray | np.ndarray], all sharing batch dim 0
- ``non_tensors``: dict[str, np.ndarray(dtype=object)] for ragged/py data
  (raw prompt strings, per-sample reward metadata, …)
- ``meta_info``: dict of scalars/config riding along with the batch

supporting the full verbs the reference needs: select / union / concat /
split / chunk / index / slice / repeat / rename / pop, plus device_put with
a sharding. Registered as a pytree so it can flow through jit (tensors are
leaves; non_tensors/meta ride as aux data — they must be hashable-stable
across calls used inside jit, so prefer keeping them out of jit'd fns).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Iterator, Sequence

import jax
import numpy as np


def _batch_size_of(tensors: dict[str, Any], non_tensors: dict[str, Any]) -> int | None:
    for v in tensors.values():
        return int(v.shape[0])
    for v in non_tensors.values():
        return int(v.shape[0])
    return None


@dataclass
class TensorBatch:
    tensors: dict[str, Any] = field(default_factory=dict)
    non_tensors: dict[str, Any] = field(default_factory=dict)
    meta_info: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.check_consistency()

    # -- basic protocol ----------------------------------------------------

    def check_consistency(self) -> None:
        bs = _batch_size_of(self.tensors, self.non_tensors)
        if bs is None:
            return
        for k, v in self.tensors.items():
            if int(v.shape[0]) != bs:
                raise ValueError(f"tensor {k!r} batch dim {v.shape[0]} != {bs}")
        for k in list(self.non_tensors):
            v = self.non_tensors[k]
            if not isinstance(v, np.ndarray):
                v = np.array(v, dtype=object)
                self.non_tensors[k] = v
            if int(v.shape[0]) != bs:
                raise ValueError(f"non_tensor {k!r} batch dim {v.shape[0]} != {bs}")

    def __len__(self) -> int:
        bs = _batch_size_of(self.tensors, self.non_tensors)
        return 0 if bs is None else bs

    def __contains__(self, key: str) -> bool:
        return key in self.tensors or key in self.non_tensors

    def __getitem__(self, item):
        if isinstance(item, str):
            if item in self.tensors:
                return self.tensors[item]
            return self.non_tensors[item]
        if isinstance(item, (slice, list, np.ndarray)):
            idx = np.arange(len(self))[item] if isinstance(item, slice) else np.asarray(item)
            return self.index(idx)
        if isinstance(item, int):
            return self.index(np.array([item]))
        raise TypeError(f"bad index: {item!r}")

    def keys(self):
        return [*self.tensors.keys(), *self.non_tensors.keys()]

    # -- verbs -------------------------------------------------------------

    @classmethod
    def from_dict(
        cls,
        tensors: dict[str, Any] | None = None,
        non_tensors: dict[str, Any] | None = None,
        meta_info: dict[str, Any] | None = None,
    ) -> "TensorBatch":
        non_tensors = {
            k: (v if isinstance(v, np.ndarray) and v.dtype == object else np.array(list(v), dtype=object))
            for k, v in (non_tensors or {}).items()
        }
        return cls(dict(tensors or {}), non_tensors, dict(meta_info or {}))

    def select(self, tensor_keys: Sequence[str] | None = None,
               non_tensor_keys: Sequence[str] | None = None,
               meta_info_keys: Sequence[str] | None = None,
               deepcopy_meta: bool = False) -> "TensorBatch":
        tensors = (
            {k: self.tensors[k] for k in tensor_keys}
            if tensor_keys is not None
            else dict(self.tensors)
        )
        non_tensors = (
            {k: self.non_tensors[k] for k in non_tensor_keys}
            if non_tensor_keys is not None
            else dict(self.non_tensors)
        )
        meta = (
            {k: self.meta_info[k] for k in meta_info_keys}
            if meta_info_keys is not None
            else dict(self.meta_info)
        )
        if deepcopy_meta:
            meta = copy.deepcopy(meta)
        return TensorBatch(tensors, non_tensors, meta)

    def pop(self, tensor_keys: Sequence[str] = (), non_tensor_keys: Sequence[str] = ()) -> "TensorBatch":
        out_t = {k: self.tensors.pop(k) for k in tensor_keys}
        out_nt = {k: self.non_tensors.pop(k) for k in non_tensor_keys}
        return TensorBatch(out_t, out_nt, dict(self.meta_info))

    def union(self, other: "TensorBatch") -> "TensorBatch":
        """Merge another batch's keys into this one (same batch size).

        Key collisions must refer to identical objects/shapes (verl union
        semantics); later keys win for meta_info.
        """
        if len(self) and len(other) and len(self) != len(other):
            raise ValueError(f"union size mismatch {len(self)} vs {len(other)}")
        tensors = {**self.tensors, **other.tensors}
        non_tensors = {**self.non_tensors, **other.non_tensors}
        meta = {**self.meta_info, **other.meta_info}
        return TensorBatch(tensors, non_tensors, meta)

    @staticmethod
    def concat(batches: Sequence["TensorBatch"]) -> "TensorBatch":
        batches = [b for b in batches if len(b) > 0]
        if not batches:
            return TensorBatch()
        keys = batches[0].tensors.keys()
        tensors = {}
        for k in keys:
            vals = [b.tensors[k] for b in batches]
            if any(isinstance(v, jax.Array) for v in vals):
                tensors[k] = jax.numpy.concatenate([jax.numpy.asarray(v) for v in vals], axis=0)
            else:
                tensors[k] = np.concatenate(vals, axis=0)
        non_tensors = {
            k: np.concatenate([b.non_tensors[k] for b in batches], axis=0)
            for k in batches[0].non_tensors
        }
        return TensorBatch(tensors, non_tensors, dict(batches[0].meta_info))

    def index(self, idx: np.ndarray) -> "TensorBatch":
        tensors = {k: v[idx] for k, v in self.tensors.items()}
        non_tensors = {k: v[idx] for k, v in self.non_tensors.items()}
        return TensorBatch(tensors, non_tensors, dict(self.meta_info))

    def split(self, split_size: int) -> list["TensorBatch"]:
        n = len(self)
        return [self.index(np.arange(i, min(i + split_size, n))) for i in range(0, n, split_size)]

    def chunk(self, chunks: int) -> list["TensorBatch"]:
        n = len(self)
        if n % chunks != 0:
            raise ValueError(f"batch size {n} not divisible into {chunks} chunks")
        return self.split(n // chunks)

    def repeat(self, repeat_times: int, interleave: bool = True) -> "TensorBatch":
        """Unroll each row ``repeat_times`` times (reference n-samples-per-prompt
        unroll, sglang_rollout_remote.py:198-225)."""
        n = len(self)
        if interleave:
            idx = np.repeat(np.arange(n), repeat_times)
        else:
            idx = np.tile(np.arange(n), repeat_times)
        return self.index(idx)

    def rename(self, old_keys: Sequence[str], new_keys: Sequence[str]) -> "TensorBatch":
        for o, nk in zip(old_keys, new_keys):
            if o in self.tensors:
                self.tensors[nk] = self.tensors.pop(o)
            elif o in self.non_tensors:
                self.non_tensors[nk] = self.non_tensors.pop(o)
        return self

    def to_device(self, sharding=None) -> "TensorBatch":
        """device_put every tensor (optionally with a NamedSharding)."""
        tensors = {
            k: jax.device_put(v, sharding) if sharding is not None else jax.device_put(v)
            for k, v in self.tensors.items()
        }
        return TensorBatch(tensors, self.non_tensors, self.meta_info)

    def to_numpy(self) -> "TensorBatch":
        tensors = {k: np.asarray(v) for k, v in self.tensors.items()}
        return TensorBatch(tensors, self.non_tensors, self.meta_info)


def _tb_flatten(tb: TensorBatch):
    keys = sorted(tb.tensors.keys())
    children = tuple(tb.tensors[k] for k in keys)
    # aux data must be hashable for jit treedef equality: object arrays are
    # converted to nested tuples (fine for the str/scalar payloads the
    # trainer carries); unhashable non_tensor payloads should stay out of
    # jit'd functions.
    nt_keys = tuple(sorted(tb.non_tensors.keys()))
    nt_vals = tuple(tuple(tb.non_tensors[k].tolist()) for k in nt_keys)
    aux = (tuple(keys), nt_keys, nt_vals,
           tuple(sorted(tb.meta_info.items(), key=lambda kv: kv[0])))
    return children, aux


def _tb_unflatten(aux, children):
    keys, nt_keys, nt_vals, meta_items = aux
    tb = TensorBatch.__new__(TensorBatch)
    tb.tensors = dict(zip(keys, children))
    tb.non_tensors = {
        k: np.array(list(v), dtype=object) for k, v in zip(nt_keys, nt_vals)
    }
    tb.meta_info = dict(meta_items)
    return tb


jax.tree_util.register_pytree_node(TensorBatch, _tb_flatten, _tb_unflatten)
