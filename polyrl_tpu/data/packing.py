"""Packed-sequence (remove-padding) training support.

The reference trains varlen-packed via ``use_remove_padding`` + flash-attn
varlen (``/root/reference/rlboost/verl_stream/workers/actor/
stream_dp_actor.py:41-47``; recipe ``run_async_grpo_pipeline.sh:29``) and
splits micro-batches by token budget, not trajectory count
(``prepare_dynamic_batch`` ``stream_dp_actor.py:35,136``; ``_balance_batch``
``stream_ray_trainer.py:406-410``, 16,384 tok/GPU in the recipe). With a
14,336-token response budget and highly variable lengths, fixed
``[B, Tp+Tr]`` padded batches waste most of the FLOPs on pads.

TPU-first shape discipline: XLA wants STATIC shapes, so instead of true
ragged varlen this packs trajectories into a FIXED ``[n_rows, pack_len]``
grid with segment ids (the Pallas flash kernel takes them —
``ops/flash.py``), and emits micro-batches of that fixed shape: one
compilation, near-zero padding. A micro's token budget is
``n_rows * pack_len``; bins are filled greedily in stream order so group
boundaries (GRPO) stay intact across micros.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from polyrl_tpu.data.batch import TensorBatch


@dataclasses.dataclass
class PackSpec:
    """Where each packed trajectory's RESPONSE tokens live.

    Arrays are aligned per-trajectory: trajectory ``orig_idx[j]`` of the
    source batch sits in packed row ``row[j]``; its response tokens occupy
    columns ``[resp_start[j], resp_start[j] + resp_len[j])``.
    """

    orig_idx: np.ndarray
    row: np.ndarray
    resp_start: np.ndarray
    resp_len: np.ndarray
    n_rows: int
    pack_len: int

    def scatter(self, field: np.ndarray, dtype=None) -> np.ndarray:
        """[B, Tr] padded per-response-token field -> packed [R, L]."""
        out = np.zeros((self.n_rows, self.pack_len),
                       dtype or np.asarray(field).dtype)
        for j in range(len(self.orig_idx)):
            n = self.resp_len[j]
            out[self.row[j], self.resp_start[j]:self.resp_start[j] + n] = \
                field[self.orig_idx[j], :n]
        return out

    def gather(self, packed: np.ndarray, t_resp: int) -> np.ndarray:
        """Packed [R, L] per-token field -> padded [B_src, Tr] (rows not in
        this pack stay zero; caller accumulates across packs)."""
        b = int(self.orig_idx.max()) + 1 if len(self.orig_idx) else 0
        out = np.zeros((b, t_resp), np.asarray(packed).dtype)
        self.gather_into(packed, out)
        return out

    def gather_into(self, packed: np.ndarray, out: np.ndarray) -> None:
        packed = np.asarray(packed)
        for j in range(len(self.orig_idx)):
            n = self.resp_len[j]
            out[self.orig_idx[j], :n] = \
                packed[self.row[j], self.resp_start[j]:self.resp_start[j] + n]


def _trajectory_tokens(batch: TensorBatch, t_prompt: int):
    """Per-trajectory (prompt_tokens, response_tokens) from the padded
    layout: prompts left-padded in input_ids[:, :Tp], responses right-padded
    in responses/response_mask."""
    input_ids = np.asarray(batch["input_ids"])
    attn = np.asarray(batch["attention_mask"])
    responses = np.asarray(batch["responses"])
    resp_mask = np.asarray(batch["response_mask"])
    prompts, resps = [], []
    for i in range(len(input_ids)):
        p = input_ids[i, :t_prompt][attn[i, :t_prompt] > 0]
        n = int(resp_mask[i].sum())
        prompts.append(p)
        resps.append(responses[i, :n])
    return prompts, resps


def iter_packed_micros(
    batch: TensorBatch,
    t_prompt: int,
    pack_len: int,
    n_rows: int,
    pad_id: int,
    scatter_keys: tuple[str, ...] = (),
):
    """Yield ``(packed TensorBatch, PackSpec)`` micro-batches of fixed shape
    [n_rows, pack_len], greedily filling bins IN STREAM ORDER (trajectories
    are never reordered, so GRPO groups stay contiguous and minibatch
    boundaries remain meaningful).

    Packed tensors: input_ids, positions (restart per segment), segment_ids
    (1-based, 0 = pad), attention_mask (validity), loss_mask (response
    tokens — the packed response_mask), plus ``scatter_keys`` ([B, Tr]
    per-response-token fields scattered into the packed layout).
    """
    prompts, resps = _trajectory_tokens(batch, t_prompt)
    n = len(prompts)
    i = 0
    while i < n:
        # fill up to n_rows bins first-fit in order
        fill = np.zeros(n_rows, np.int64)
        segs = [[] for _ in range(n_rows)]  # (traj_idx, start, p_len, r_len)
        placed_any = False
        while i < n:
            need = len(prompts[i]) + len(resps[i])
            if need > pack_len:
                raise ValueError(
                    f"trajectory {i} length {need} exceeds pack_len {pack_len}")
            fits = np.flatnonzero(fill + need <= pack_len)
            if len(fits) == 0:
                break
            r = int(fits[0])
            segs[r].append((i, int(fill[r]), len(prompts[i]), len(resps[i])))
            fill[r] += need
            placed_any = True
            i += 1
        if not placed_any:
            raise AssertionError("packing made no progress")
        yield _build_pack(batch, prompts, resps, segs, pack_len, n_rows,
                          pad_id, scatter_keys)


def _build_pack(batch, prompts, resps, segs, pack_len, n_rows, pad_id,
                scatter_keys):
    input_ids = np.full((n_rows, pack_len), pad_id, np.int32)
    positions = np.zeros((n_rows, pack_len), np.int32)
    segment_ids = np.zeros((n_rows, pack_len), np.int32)
    loss_mask = np.zeros((n_rows, pack_len), np.float32)
    oi, rw, rs, rl = [], [], [], []
    for r in range(n_rows):
        for s_idx, (ti, start, p_len, r_len) in enumerate(segs[r]):
            tot = p_len + r_len
            input_ids[r, start:start + p_len] = prompts[ti]
            input_ids[r, start + p_len:start + tot] = resps[ti]
            positions[r, start:start + tot] = np.arange(tot)
            segment_ids[r, start:start + tot] = s_idx + 1
            loss_mask[r, start + p_len:start + tot] = 1.0
            oi.append(ti)
            rw.append(r)
            rs.append(start + p_len)
            rl.append(r_len)
    spec = PackSpec(np.asarray(oi), np.asarray(rw), np.asarray(rs),
                    np.asarray(rl), n_rows, pack_len)
    tensors = {
        "input_ids": input_ids,
        "positions": positions,
        "segment_ids": segment_ids,
        "attention_mask": (segment_ids > 0).astype(np.float32),
        "loss_mask": loss_mask,
    }
    for k in scatter_keys:
        tensors[k] = spec.scatter(np.asarray(batch[k]))
    return TensorBatch.from_dict(tensors=tensors), spec


def packing_efficiency(specs: list[PackSpec], prompts_resps_tokens: int,
                       n_rows: int, pack_len: int) -> float:
    """real tokens / padded grid capacity across all packs."""
    cap = sum(1 for _ in specs) * n_rows * pack_len
    return prompts_resps_tokens / cap if cap else 0.0
