"""RL prompt datasets + samplers.

Equivalent of the reference's dataset layer (verl ``RLHFDataset`` +
``create_rl_dataset``/``create_rl_sampler``, reference
``main_ppo.py:348-439``; OpenR1 preprocessing ``examples/data_preprocess/
openr1.py:26-88``). Sources: in-memory records, JSONL, or parquet (via
pyarrow when present). Each record carries ``prompt``, ``ground_truth``,
``data_source`` and optional ``extra_info`` — the fields the reward layer
dispatches on (SURVEY.md C17).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Iterator, Sequence

import numpy as np


@dataclass
class RLDataset:
    records: list[dict]

    @classmethod
    def from_jsonl(cls, path: str) -> "RLDataset":
        with open(path) as f:
            return cls([json.loads(line) for line in f if line.strip()])

    @classmethod
    def from_parquet(cls, path: str, prompt_key: str = "prompt") -> "RLDataset":
        import pyarrow.parquet as pq  # optional dep, present with pandas stacks

        records = pq.read_table(path).to_pylist()
        for r in records:
            if prompt_key != "prompt":
                r["prompt"] = r.get(prompt_key, r.get("prompt", ""))
            # preprocess scripts store extra_info as a JSON string to keep
            # the parquet schema flat; decode back to a dict
            if isinstance(r.get("extra_info"), str):
                try:
                    r["extra_info"] = json.loads(r["extra_info"])
                except ValueError:
                    pass
        return cls(records)

    def __len__(self) -> int:
        return len(self.records)

    def __getitem__(self, i: int) -> dict:
        return self.records[i]


def make_sampler(n: int, kind: str = "random", seed: int = 0,
                 scores: Sequence[float] | None = None) -> Iterator[int]:
    """random | sequential | curriculum index stream (reference
    create_rl_sampler, main_ppo.py:398-439). Curriculum orders by
    ``scores`` ascending (easy→hard) on the first epoch, then anneals to
    random shuffles — the reference's curriculum sampler contract."""
    rng = random.Random(seed)
    first = True
    while True:
        order = list(range(n))
        if kind == "curriculum" and scores is not None and first:
            order.sort(key=lambda i: scores[i])
        elif kind in ("random", "curriculum"):
            rng.shuffle(order)
        first = False
        yield from order


class PromptDataLoader:
    """Batches of raw records; stateful for checkpoint/resume (the reference
    uses StatefulDataLoader, stream_ray_trainer.py:38)."""

    def __init__(self, dataset: RLDataset, batch_size: int, shuffle: bool = True,
                 seed: int = 0, sampler_kind: str | None = None,
                 curriculum_key: str = "difficulty"):
        self.dataset = dataset
        self.batch_size = batch_size
        kind = sampler_kind or ("random" if shuffle else "sequential")
        scores = None
        if kind == "curriculum":
            scores = [float((r.get("extra_info") or {}).get(curriculum_key, 0.0))
                      for r in dataset.records]
        self.sampler = make_sampler(len(dataset), kind, seed, scores=scores)
        self.consumed = 0

    def state_dict(self) -> dict:
        return {"consumed": self.consumed}

    def load_state_dict(self, state: dict) -> None:
        for _ in range(state["consumed"]):
            next(self.sampler)
        self.consumed = state["consumed"]

    def __iter__(self):
        return self

    def __next__(self) -> list[dict]:
        batch = [self.dataset[next(self.sampler)] for _ in range(self.batch_size)]
        self.consumed += self.batch_size
        return batch


# -- synthetic arithmetic task for e2e tests/benchmarks ---------------------


def make_arithmetic_dataset(n: int = 512, seed: int = 0, lo: int = 0, hi: int = 20) -> RLDataset:
    """Tiny addition task: trainable end-to-end with the ByteTokenizer.
    Serves the role of GSM8K in environments with no dataset downloads."""
    rng = random.Random(seed)
    records = []
    for _ in range(n):
        a, b = rng.randint(lo, hi), rng.randint(lo, hi)
        records.append(
            {
                "prompt": f"{a}+{b}=",
                "ground_truth": str(a + b),
                "data_source": "gsm8k",  # routes to the gsm8k scorer (flexible)
            }
        )
    return RLDataset(records)
