"""Paged decode attention: one query token per sequence over a paged KV pool.

TPU-native replacement for the reference's SGLang paged-KV CUDA decode
kernels (SURVEY.md §2.2 native-census row 1). The KV cache is a pool of
fixed-size pages shared by all running sequences; each sequence owns an
ordered page list (its row of ``page_table``). This is what makes
continuous batching work: sequences of wildly different lengths share one
static-shaped pool, so ONE compiled decode step serves every mix of
requests — no shape buckets, no recompilation as requests come and go.

Two implementations with identical semantics:

- ``paged_attention_ref`` — jnp gather + dense softmax. XLA-compilable
  everywhere; the correctness oracle and the CPU-test path.
- ``paged_attention_pallas`` — Pallas TPU kernel. Grid (seq, kv_head,
  page); the page table is a scalar-prefetch operand, so each grid step's
  BlockSpec index_map DMAs exactly the page it needs from HBM into VMEM
  (automatic double-buffering from the pipeline emitter). Online softmax
  accumulates in VMEM scratch across the page axis; invalid pages are
  skipped with ``pl.when`` (their index_map points at the reserved null
  page 0, whose DMA cost is the price of a uniform grid).

Shared-prefix GROUPED decode (``grouped_paged_attention*``): GRPO's
G-samples-per-prompt traffic means G slots share one physical prompt-KV
prefix (page-table indirection since the group-shared prefill layer).
The per-slot kernel above still streams those prefix pages from HBM once
PER SLOT — a G× redundant read of the dominant KV segment of a decode
step that is bandwidth-bound. The grouped variant is two-phase:

- **Phase 1 (prefix)**: grid (group, prefix_page) — each shared prefix
  page is DMA'd ONCE per group and attends against the group's G·rep
  stacked decode queries (a [G·rep, page] MXU matmul instead of G rep-row
  gemvs — arithmetic intensity ×G). Emits per-slot partial flash stats
  (m, l, unnormalized acc).
- **Phase 2 (suffix)**: the per-slot kernel shape, over each slot's OWN
  pages past the prefix (prompt tail + generated KV), with the online
  softmax INITIALIZED from phase 1's stats — the standard flash (m, l,
  acc) log-sum-exp merge falls out of the rescale the kernel already
  does per page. Ungrouped slots init with (m=-inf, l=0, acc=0) and
  phase 2 degenerates to exactly the ungrouped kernel's math.

``grouped_paged_attention_ref`` is the jnp oracle for the same two-phase
split (used by CPU tests and as the engine's CPU path); the result is
mathematically the plain full-table attention, so it is pinned against
``paged_attention_ref`` on the reconstructed per-slot tables.

Layout notes (why these shapes):
- pools are [num_pages, page_size, Hkv, D]: page_size×D are the tiled
  (sublane×lane) dims of each DMA; Hkv is a grid axis so one kernel
  instance streams a [page_size, D] tile — MXU-shaped for the q·kᵀ matmul.
- q is pre-reshaped to [S, Hkv, rep, D] (rep = GQA group size): the kernel
  computes a [rep, page_size] logits tile per page — contraction over D
  lands on the MXU without any in-kernel head regrouping.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from polyrl_tpu.parallel.compat import shard_map

NEG_INF = float(np.finfo(np.float32).min)


def paged_attention_ref(
    q: jnp.ndarray,        # [S, Hq, D]
    k_pool: jnp.ndarray,   # [Hkv, N_pages, page_size, D]
    v_pool: jnp.ndarray,   # [Hkv, N_pages, page_size, D]
    page_table: jnp.ndarray,  # [S, P] int32 page ids (0 = null page ok)
    seq_lens: jnp.ndarray,    # [S] int32 valid tokens per sequence
    scale: float | None = None,
) -> jnp.ndarray:
    """Gather-based oracle. Returns [S, Hq, D] in q.dtype."""
    s, hq, d = q.shape
    hkv, n_pages, ps, _ = k_pool.shape
    p = page_table.shape[1]
    rep = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    k = k_pool[:, page_table].reshape(hkv, s, p * ps, d)  # [Hkv, S, T, D]
    v = v_pool[:, page_table].reshape(hkv, s, p * ps, d)
    qr = q.reshape(s, hkv, rep, d).astype(jnp.float32)

    logits = jnp.einsum("shrd,hstd->shrt", qr, k.astype(jnp.float32)) * scale
    pos = jnp.arange(p * ps)[None, :]  # [1, T]
    valid = pos < jnp.maximum(seq_lens, 1)[:, None]  # clamp: empty rows stay finite
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("shrt,hstd->shrd", probs, v.astype(jnp.float32))
    return out.reshape(s, hq, d).astype(q.dtype)


def _paged_attn_kernel(page_tbl_ref, seq_lens_ref,  # scalar prefetch
                       q_ref,      # [1, Hkv, rep, D]
                       k_ref,      # [Hkv, 1, page_size, D]
                       v_ref,      # [Hkv, 1, page_size, D]
                       out_ref,    # [1, Hkv, rep, D]
                       m_ref, l_ref, acc_ref,  # VMEM [Hkv, rep_pad, 128|D]
                       *, page_size: int, scale: float):
    """One (slot, page) program computing ALL kv-head groups at once:
    Mosaic requires the last two block dims be (8,128)-tileable or full, so
    the kv-head axis must ride whole inside the block (blocking it to 1 is
    rejected on real TPUs — only interpret mode accepted it)."""
    import jax.experimental.pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(1)
    seq_len = seq_lens_ref[s]
    n_pages = (jnp.maximum(seq_len, 1) + page_size - 1) // page_size

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(p < n_pages)
    def _work():
        q = q_ref[0].astype(jnp.float32)   # [Hkv, rep, D]
        # pool is head-major: the page block arrives as [Hkv, 1, page, D]
        k = k_ref[:, 0].astype(jnp.float32)  # [Hkv, page_size, D]
        v = v_ref[:, 0].astype(jnp.float32)  # [Hkv, page_size, D]
        rep = q.shape[1]

        logits = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [Hkv, rep, page_size]
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 2)
        logits = jnp.where(pos < jnp.maximum(seq_len, 1), logits, NEG_INF)

        m_prev = m_ref[:, :rep, :1]                    # [Hkv, rep, 1]
        l_prev = l_ref[:, :rep, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(logits - m_new)                # [Hkv, rep, page_size]
        l_new = alpha * l_prev + jnp.sum(probs, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            probs, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [Hkv, rep, D]
        acc_ref[:, :rep, :] = acc_ref[:, :rep, :] * alpha + pv
        m_ref[:, :rep, :1] = m_new
        l_ref[:, :rep, :1] = l_new

    @pl.when(p == n_pages - 1)
    def _finish():
        rep = out_ref.shape[2]
        out_ref[0] = (
            acc_ref[:, :rep, :] / jnp.maximum(l_ref[:, :rep, :1], 1e-30)
        ).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_attention_pallas(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    seq_lens: jnp.ndarray,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, hq, d = q.shape
    hkv, n_pool, page_size, _ = k_pool.shape
    p = page_table.shape[1]
    rep = hq // hkv
    scale = scale if scale is not None else d ** -0.5
    rep_pad = max(rep, 8)  # f32 sublane tile

    qr = q.reshape(s, hkv, rep, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, p),
        in_specs=[
            pl.BlockSpec((1, hkv, rep, d), lambda si, pi, pt, sl: (si, 0, 0, 0)),
            pl.BlockSpec((hkv, 1, page_size, d),
                         lambda si, pi, pt, sl: (0, pt[si, pi], 0, 0)),
            pl.BlockSpec((hkv, 1, page_size, d),
                         lambda si, pi, pt, sl: (0, pt[si, pi], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hkv, rep, d),
                               lambda si, pi, pt, sl: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, rep_pad, 128), jnp.float32),  # m (col 0 used)
            pltpu.VMEM((hkv, rep_pad, 128), jnp.float32),  # l
            pltpu.VMEM((hkv, rep_pad, d), jnp.float32),    # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, page_size=page_size, scale=scale),
        out_shape=jax.ShapeDtypeStruct((s, hkv, rep, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32), qr, k_pool, v_pool)
    return out.reshape(s, hq, d)


def paged_attention_lib(q, k_pool, v_pool, page_table, seq_lens, scale=None):
    """The tuned multi-page kernel from jax.experimental.pallas.ops.tpu:
    processes ``pages_per_compute_block`` pages per grid step with
    double-buffered page DMAs, so HBM bandwidth is actually saturated (our
    one-page-per-step kernel bottoms out near 90 GB/s on real chips — fine
    as a readable oracle, 8-9x off as the production path). The pool layout
    [Hkv, N, page, D] is exactly the kernel's native layout; the kernel
    applies no softmax scale, so q is pre-scaled here."""
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        paged_attention as _pa)

    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    p = page_table.shape[1]
    ppcb = min(8, p)
    while p % ppcb:
        ppcb -= 1
    return _pa(
        (q * scale).astype(q.dtype), k_pool, v_pool,
        jnp.maximum(seq_lens.astype(jnp.int32), 1),
        page_table.astype(jnp.int32),
        pages_per_compute_block=ppcb)


# -- shared-prefix grouped decode attention ---------------------------------


def _group_slot_maps(group_slots, group_prefix_lens, s: int, page_size: int):
    """Invert the group table into per-slot maps (jit-safe, static shapes).

    group_slots [NG, G] int32 (-1 = empty seat) → for each of the ``s``
    attention rows: the group row it sits in (-1 = ungrouped), its seat
    column, and the number of leading page-table columns phase 1 already
    covered (0 for ungrouped rows). Scatter uses mode="drop" so the -1
    seats (routed out of bounds) cannot clamp-corrupt the last slot.
    """
    ng, gmax = group_slots.shape
    flat = group_slots.reshape(-1)
    gidx = jnp.repeat(jnp.arange(ng, dtype=jnp.int32), gmax)
    gcol = jnp.tile(jnp.arange(gmax, dtype=jnp.int32), ng)
    tgt = jnp.where(flat >= 0, flat, s)  # s = out of bounds → dropped
    slot_grp = jnp.full((s,), -1, jnp.int32).at[tgt].set(gidx, mode="drop")
    slot_col = jnp.zeros((s,), jnp.int32).at[tgt].set(gcol, mode="drop")
    pre_tok = group_prefix_lens[jnp.clip(slot_grp, 0, ng - 1)]
    slot_npre = jnp.where(slot_grp >= 0, pre_tok // page_size, 0)
    return slot_grp, slot_col, slot_npre.astype(jnp.int32)


def grouped_paged_attention_ref(
    q: jnp.ndarray,               # [S, Hq, D]
    k_pool: jnp.ndarray,          # [Hkv, N_pages, page_size, D]
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,      # [S, P] int32 — FULL per-slot page rows
    seq_lens: jnp.ndarray,        # [S] int32 — attended tokens per slot
    group_slots: jnp.ndarray,     # [NG, G] int32 slot ids, -1 = empty seat
    group_prefix_pages: jnp.ndarray,  # [NG, P_pre] int32 shared prefix pages
    group_prefix_lens: jnp.ndarray,   # [NG] int32 prefix TOKENS (page-mult.)
    scale: float | None = None,
) -> jnp.ndarray:
    """Two-phase oracle: explicit prefix/suffix split + LSE merge in jnp.

    Contract (what the engine guarantees): for every seated slot ``s`` of
    group ``g``, ``page_table[s, :n_pre] == group_prefix_pages[g, :n_pre]``
    (the PR-8 page-table indirection) and ``seq_lens[s] > prefix_len`` —
    so the merged result equals plain full-table attention up to float
    reduction order. Unseated slots take the phase-2-only path and match
    ``paged_attention_ref`` exactly.
    """
    s, hq, d = q.shape
    hkv, _n, ps, _ = k_pool.shape
    p = page_table.shape[1]
    ng, _g = group_slots.shape
    p_pre = group_prefix_pages.shape[1]
    rep = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    slot_grp, _slot_col, slot_npre = _group_slot_maps(
        group_slots, group_prefix_lens, s, ps)
    pre_tok = (slot_npre * ps)[:, None]                   # [S, 1]
    qr = q.reshape(s, hkv, rep, d).astype(jnp.float32)

    # phase 1: every slot against ITS group's shared prefix (ungrouped
    # slots fully masked → explicit zero/neg-inf stats below)
    gi = jnp.clip(slot_grp, 0, ng - 1)
    kp = k_pool[:, group_prefix_pages].reshape(hkv, ng, p_pre * ps, d)
    vp = v_pool[:, group_prefix_pages].reshape(hkv, ng, p_pre * ps, d)
    kp_s, vp_s = kp[:, gi], vp[:, gi]                     # [Hkv, S, T1, D]
    logits1 = jnp.einsum("shrd,hstd->shrt", qr,
                         kp_s.astype(jnp.float32)) * scale
    pos1 = jnp.arange(p_pre * ps)[None, :]
    valid1 = pos1 < pre_tok                               # [S, T1]
    logits1 = jnp.where(valid1[:, None, None, :], logits1, NEG_INF)
    m1 = jnp.max(logits1, axis=-1)                        # [S, Hkv, rep]
    e1 = jnp.exp(logits1 - m1[..., None])
    e1 = jnp.where(valid1[:, None, None, :], e1, 0.0)
    l1 = jnp.sum(e1, axis=-1)
    acc1 = jnp.einsum("shrt,hstd->shrd", e1, vp_s.astype(jnp.float32))
    grouped = (slot_grp >= 0)[:, None, None]
    m1 = jnp.where(grouped, m1, NEG_INF)
    l1 = jnp.where(grouped, l1, 0.0)
    acc1 = jnp.where(grouped[..., None], acc1, 0.0)

    # phase 2: each slot's own pages PAST the prefix
    k2 = k_pool[:, page_table].reshape(hkv, s, p * ps, d)
    v2 = v_pool[:, page_table].reshape(hkv, s, p * ps, d)
    logits2 = jnp.einsum("shrd,hstd->shrt", qr,
                         k2.astype(jnp.float32)) * scale
    pos2 = jnp.arange(p * ps)[None, :]
    valid2 = ((pos2 >= pre_tok)
              & (pos2 < jnp.maximum(seq_lens, 1)[:, None]))
    logits2 = jnp.where(valid2[:, None, None, :], logits2, NEG_INF)
    m2 = jnp.max(logits2, axis=-1)
    e2 = jnp.exp(logits2 - m2[..., None])
    e2 = jnp.where(valid2[:, None, None, :], e2, 0.0)
    l2 = jnp.sum(e2, axis=-1)
    acc2 = jnp.einsum("shrt,hstd->shrd", e2, v2.astype(jnp.float32))

    # LSE merge (NEG_INF is finite, so the alphas stay NaN-free: an empty
    # side contributes l=0 and its alpha multiplies nothing)
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = a1 * l1 + a2 * l2
    acc = a1[..., None] * acc1 + a2[..., None] * acc2
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(s, hq, d).astype(q.dtype)


def _grouped_prefix_kernel(pre_pages_ref, pre_lens_ref,  # scalar prefetch
                           q_ref,      # [1, Hkv, GR, D] (group's stacked q)
                           k_ref,      # [Hkv, 1, page_size, D]
                           v_ref,
                           acc_out_ref,  # [1, Hkv, GR, D] f32 unnormalized
                           m_out_ref,    # [1, Hkv, GR, 128] f32 (col 0)
                           l_out_ref,
                           m_ref, l_ref, acc_ref,  # VMEM scratch
                           *, page_size: int, scale: float):
    """Phase 1: one (group, prefix_page) program. The page block is DMA'd
    once and attends against ALL G·rep stacked queries of the group — the
    HBM stream the per-slot kernel pays G times happens once, and the
    q·kᵀ contraction is a [GR, page] MXU matmul. Outputs are the group's
    flash stats; normalization happens in phase 2's merge. Empty seats /
    GR padding compute garbage rows that no slot ever gathers."""
    import jax.experimental.pallas as pl

    g = pl.program_id(0)
    p = pl.program_id(1)
    pre_len = pre_lens_ref[g]
    n_pages = (pre_len + page_size - 1) // page_size  # 0 for pad group rows

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(p < n_pages)
    def _work():
        q = q_ref[0].astype(jnp.float32)     # [Hkv, GR, D]
        k = k_ref[:, 0].astype(jnp.float32)  # [Hkv, page_size, D]
        v = v_ref[:, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [Hkv, GR, page]
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 2)
        logits = jnp.where(pos < pre_len, logits, NEG_INF)

        m_prev = m_ref[:, :, :1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(logits - m_new)
        l_new = alpha * l_prev + jnp.sum(probs, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            probs, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)          # [Hkv, GR, D]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:, :, :1] = m_new
        l_ref[:, :, :1] = l_new

    @pl.when((p == n_pages - 1) & (n_pages > 0))
    def _finish():
        acc_out_ref[0] = acc_ref[:]
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def _grouped_suffix_kernel(pt_ref, lens_ref, npre_ref,  # scalar prefetch
                           q_ref,     # [1, Hkv, rep, D]
                           m1_ref,    # [1, Hkv, rep_pad, 128] phase-1 m
                           l1_ref,
                           acc1_ref,  # [1, Hkv, rep_pad, D]
                           k_ref, v_ref,
                           out_ref,
                           m_ref, l_ref, acc_ref,  # VMEM scratch
                           *, page_size: int, scale: float):
    """Phase 2: the per-slot kernel over the slot's pages PAST its phase-1
    prefix (page column ``npre + p``), with the online-softmax state
    INITIALIZED from phase 1's (m, l, acc) — the rescale every page
    iteration already performs IS the flash log-sum-exp merge. Ungrouped
    slots arrive with (NEG_INF, 0, 0) and reduce to the plain kernel."""
    import jax.experimental.pallas as pl

    s = pl.program_id(0)
    p = pl.program_id(1)
    seq_len = lens_ref[s]
    npre = npre_ref[s]
    n_tot = (jnp.maximum(seq_len, 1) + page_size - 1) // page_size
    n_sfx = jnp.maximum(n_tot - npre, 1)  # active slots always own >= 1

    @pl.when(p == 0)
    def _init():
        m_ref[:] = m1_ref[0]
        l_ref[:] = l1_ref[0]
        acc_ref[:] = acc1_ref[0]

    @pl.when(p < n_sfx)
    def _work():
        q = q_ref[0].astype(jnp.float32)     # [Hkv, rep, D]
        k = k_ref[:, 0].astype(jnp.float32)  # [Hkv, page_size, D]
        v = v_ref[:, 0].astype(jnp.float32)
        rep = q.shape[1]
        logits = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        pos = (npre + p) * page_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 2)
        logits = jnp.where(pos < jnp.maximum(seq_len, 1), logits, NEG_INF)

        m_prev = m_ref[:, :rep, :1]
        l_prev = l_ref[:, :rep, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(logits - m_new)
        l_new = alpha * l_prev + jnp.sum(probs, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            probs, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:, :rep, :] = acc_ref[:, :rep, :] * alpha + pv
        m_ref[:, :rep, :1] = m_new
        l_ref[:, :rep, :1] = l_new

    @pl.when(p == n_sfx - 1)
    def _finish():
        rep = out_ref.shape[2]
        out_ref[0] = (
            acc_ref[:, :rep, :] / jnp.maximum(l_ref[:, :rep, :1], 1e-30)
        ).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def grouped_paged_attention_pallas(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    seq_lens: jnp.ndarray,
    group_slots: jnp.ndarray,
    group_prefix_pages: jnp.ndarray,
    group_prefix_lens: jnp.ndarray,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Two pallas_calls + a small XLA gather between them.

    Phase 1 produces per-GROUP stats [NG, Hkv, G·rep, D]; the inter-phase
    gather re-keys them per SLOT ([S, Hkv, rep, D] — a few MB) so phase
    2's BlockSpec stays a plain per-slot index map and no in-kernel
    dynamic slicing (Mosaic sublane-offset restrictions) is needed.
    Ungrouped slots substitute (NEG_INF, 0, 0) in that gather.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, hq, d = q.shape
    hkv, _n_pool, page_size, _ = k_pool.shape
    p = page_table.shape[1]
    ng, gmax = group_slots.shape
    p_pre = group_prefix_pages.shape[1]
    rep = hq // hkv
    scale = scale if scale is not None else d ** -0.5
    rep_pad = max(rep, 8)
    gr = gmax * rep
    gr_pad = max(8, -(-gr // 8) * 8)

    qr = q.reshape(s, hkv, rep, d)
    slot_grp, slot_col, slot_npre = _group_slot_maps(
        group_slots, group_prefix_lens, s, page_size)

    # ---- phase 1: one stream of the shared prefix per group ----
    flat = jnp.clip(group_slots.reshape(-1), 0, s - 1)
    qg = qr[flat].reshape(ng, gmax, hkv, rep, d)
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(ng, hkv, gr, d)
    if gr_pad != gr:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gr_pad - gr), (0, 0)))
    grid1 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(ng, p_pre),
        in_specs=[
            pl.BlockSpec((1, hkv, gr_pad, d),
                         lambda gi, pi, pp, plen: (gi, 0, 0, 0)),
            pl.BlockSpec((hkv, 1, page_size, d),
                         lambda gi, pi, pp, plen: (0, pp[gi, pi], 0, 0)),
            pl.BlockSpec((hkv, 1, page_size, d),
                         lambda gi, pi, pp, plen: (0, pp[gi, pi], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hkv, gr_pad, d),
                         lambda gi, pi, pp, plen: (gi, 0, 0, 0)),
            pl.BlockSpec((1, hkv, gr_pad, 128),
                         lambda gi, pi, pp, plen: (gi, 0, 0, 0)),
            pl.BlockSpec((1, hkv, gr_pad, 128),
                         lambda gi, pi, pp, plen: (gi, 0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((hkv, gr_pad, 128), jnp.float32),  # m (col 0)
            pltpu.VMEM((hkv, gr_pad, 128), jnp.float32),  # l
            pltpu.VMEM((hkv, gr_pad, d), jnp.float32),    # acc
        ],
    )
    acc1, m1, l1 = pl.pallas_call(
        functools.partial(_grouped_prefix_kernel, page_size=page_size,
                          scale=scale),
        out_shape=[jax.ShapeDtypeStruct((ng, hkv, gr_pad, d), jnp.float32),
                   jax.ShapeDtypeStruct((ng, hkv, gr_pad, 128), jnp.float32),
                   jax.ShapeDtypeStruct((ng, hkv, gr_pad, 128), jnp.float32)],
        grid_spec=grid1,
        interpret=interpret,
    )(group_prefix_pages.astype(jnp.int32),
      group_prefix_lens.astype(jnp.int32), qg, k_pool, v_pool)

    # ---- inter-phase gather: group stats → per-slot init blocks ----
    gi = jnp.clip(slot_grp, 0, ng - 1)
    rows = (slot_col * rep)[:, None] + jnp.arange(rep)[None]   # [S, rep]
    ridx = rows[:, None, :, None]                              # [S,1,rep,1]

    def per_slot(a, fill, width):
        g = jnp.take_along_axis(
            a[gi], jnp.broadcast_to(ridx, (s, hkv, rep, width)), axis=2)
        g = jnp.where((slot_grp >= 0)[:, None, None, None], g, fill)
        if rep_pad != rep:
            g = jnp.pad(g, ((0, 0), (0, 0), (0, rep_pad - rep), (0, 0)),
                        constant_values=fill)
        return g

    m1s = per_slot(m1, NEG_INF, 128)
    l1s = per_slot(l1, 0.0, 128)
    acc1s = per_slot(acc1, 0.0, d)

    # ---- phase 2: per-slot suffix pages, merged via the init state ----
    grid2 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s, p),
        in_specs=[
            pl.BlockSpec((1, hkv, rep, d),
                         lambda si, pi, pt, sl, npre: (si, 0, 0, 0)),
            pl.BlockSpec((1, hkv, rep_pad, 128),
                         lambda si, pi, pt, sl, npre: (si, 0, 0, 0)),
            pl.BlockSpec((1, hkv, rep_pad, 128),
                         lambda si, pi, pt, sl, npre: (si, 0, 0, 0)),
            pl.BlockSpec((1, hkv, rep_pad, d),
                         lambda si, pi, pt, sl, npre: (si, 0, 0, 0)),
            pl.BlockSpec(
                (hkv, 1, page_size, d),
                lambda si, pi, pt, sl, npre:
                (0, pt[si, jnp.minimum(npre[si] + pi, pt.shape[1] - 1)],
                 0, 0)),
            pl.BlockSpec(
                (hkv, 1, page_size, d),
                lambda si, pi, pt, sl, npre:
                (0, pt[si, jnp.minimum(npre[si] + pi, pt.shape[1] - 1)],
                 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hkv, rep, d),
                               lambda si, pi, pt, sl, npre: (si, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, rep_pad, 128), jnp.float32),
            pltpu.VMEM((hkv, rep_pad, 128), jnp.float32),
            pltpu.VMEM((hkv, rep_pad, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_grouped_suffix_kernel, page_size=page_size,
                          scale=scale),
        out_shape=jax.ShapeDtypeStruct((s, hkv, rep, d), q.dtype),
        grid_spec=grid2,
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32), slot_npre,
      qr, m1s, l1s, acc1s, k_pool, v_pool)
    return out.reshape(s, hq, d)


def grouped_paged_attention(q, k_pool, v_pool, page_table, seq_lens,
                            group_slots, group_prefix_pages,
                            group_prefix_lens, scale=None):
    """Dispatch: two-phase Pallas kernels on TPU, two-phase jnp oracle
    elsewhere. Override with POLYRL_GROUPED_ATTN=ref|pallas (the ``ref``
    escape hatch also lets a TPU deployment fall back if the grouped
    lowering regresses on a new Mosaic — the ungrouped ``lib`` kernel
    remains the non-grouped dispatches' path either way)."""
    impl = os.environ.get("POLYRL_GROUPED_ATTN", "")
    if impl == "ref":
        return grouped_paged_attention_ref(
            q, k_pool, v_pool, page_table, seq_lens, group_slots,
            group_prefix_pages, group_prefix_lens, scale)
    if impl == "pallas" or jax.default_backend() == "tpu":
        return grouped_paged_attention_pallas(
            q, k_pool, v_pool, page_table, seq_lens, group_slots,
            group_prefix_pages, group_prefix_lens, scale,
            interpret=jax.default_backend() != "tpu")
    return grouped_paged_attention_ref(
        q, k_pool, v_pool, page_table, seq_lens, group_slots,
        group_prefix_pages, group_prefix_lens, scale)


def make_tp_grouped_paged_attention(mesh):
    """Tensor-parallel wrapper for the grouped kernel: q and both pools
    shard over tp on the head dim exactly like ``make_tp_paged_attention``
    (the grouped pallas calls are custom calls GSPMD cannot partition);
    the group tables are control metadata and stay replicated."""
    from jax.sharding import PartitionSpec as P

    from polyrl_tpu.parallel.mesh import TP

    def inner(q, k_pool, v_pool, page_table, seq_lens, group_slots,
              group_prefix_pages, group_prefix_lens):
        return grouped_paged_attention(
            q, k_pool, v_pool, page_table, seq_lens, group_slots,
            group_prefix_pages, group_prefix_lens)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(None, TP, None), P(TP, None, None, None),
                  P(TP, None, None, None), P(), P(), P(), P(), P()),
        out_specs=P(None, TP, None), check_vma=False)


def _kv_write_kernel(page_ref, off_ref,  # scalar prefetch
                     kpool_ref, vpool_ref, kupd_ref, vupd_ref,
                     kout_ref, vout_ref, sem_k, sem_v):
    """One program per slot: two explicit DMAs copy the slot's [Hkv, D]
    K/V rows into pool[:, page, off, :]. Every operand stays in HBM and
    the DMA engine handles the strided destination, so Mosaic's block
    tiling rules (which reject sublane-1 output blocks on real chips —
    see _paged_attn_kernel's history note) never apply."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del kpool_ref, vpool_ref  # aliased onto the outputs; never read
    s = pl.program_id(0)
    pg = page_ref[s]
    of = off_ref[s]
    ck = pltpu.make_async_copy(kupd_ref.at[s], kout_ref.at[:, pg, of], sem_k)
    cv = pltpu.make_async_copy(vupd_ref.at[s], vout_ref.at[:, pg, of], sem_v)
    ck.start()
    cv.start()
    ck.wait()
    cv.wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_kv_write_pallas(k_pool, v_pool, write_page, write_off, k_upd,
                          v_upd, interpret: bool = False):
    """Write one token's K/V per slot into the paged pools, in place.

    The XLA alternative (row scatter over [Hkv*N*ps, D], one row per
    slot*head) lowers to a serialized per-row loop on TPU — measured as
    the dominant cost of the CB decode step (2 pools x 28 layers x k fused
    steps of ~500-row scatters per dispatch). Here a Pallas grid over
    slots issues one explicit HBM->HBM DMA per pool with the
    scalar-prefetched (page, off) target — the paged-pool analogue of the
    bucketed engine's dynamic-update-slice, and the same manual-DMA shape
    TPU serving stacks use for their KV-cache update kernels. K and V are
    fused into one call to halve grid overhead. ``input_output_aliases``
    keeps the pools in place (no copy); inactive slots are pre-routed to
    null page 0 by the caller, so revisiting that row is benign (the grid
    is sequential: last write wins)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = write_page.shape[0]
    # jax-version portability: new pallas spells HBM residency
    # pltpu.MemorySpace.HBM; the legacy enum (TPUMemorySpace) has no HBM
    # member — ANY is its idiom for "stays in HBM, kernel DMAs manually"
    _ms = getattr(pltpu, "MemorySpace", None)
    hbm = pl.BlockSpec(
        memory_space=_ms.HBM if _ms is not None
        else pltpu.TPUMemorySpace.ANY)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s,),
        in_specs=[hbm, hbm, hbm, hbm],
        out_specs=[hbm, hbm],
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
    )
    return pl.pallas_call(
        _kv_write_kernel,
        out_shape=[jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                   jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)],
        grid_spec=grid_spec,
        # operand indices count the scalar-prefetch args: 0=page 1=off
        # 2=k_pool 3=v_pool (aliased onto outputs 0/1) 4=k_upd 5=v_upd
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
        # DMA targets depend on scalar-prefetched indices, never on other
        # grid steps' work; "arbitrary" keeps Mosaic from reordering
        # (CompilerParams is TPUCompilerParams on legacy pallas)
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("arbitrary",)),
    )(write_page.astype(jnp.int32), write_off.astype(jnp.int32),
      k_pool, v_pool, k_upd.astype(k_pool.dtype), v_upd.astype(v_pool.dtype))


_KV_WRITE_PROBE: dict = {}


def _pallas_kv_write_supported(hkv: int, page_size: int, d: int,
                               pool_dt, upd_dt) -> bool:
    """Eager compile+run probe of the write kernel on the active backend,
    cached per (block-shape, dtype) signature — Mosaic tiling legality
    depends on the BLOCK dims and dtypes, not on pool/grid size, so a tiny
    2-page specimen with the caller's real Hkv/page/D/dtypes decides. A
    lowering rejection must degrade to the (slow but correct) XLA scatter,
    not error every decode dispatch of a serving process. Runs on concrete
    arrays, so it is safe to trigger from inside a trace of the step fn."""
    del upd_dt  # the wrapper casts updates to pool_dt before the kernel,
    # so lowering cannot depend on it — keying on it would re-pay a ~30 s
    # tunnel probe compile for an identical kernel
    key = (hkv, page_size, d, str(pool_dt))
    if key not in _KV_WRITE_PROBE:
        try:
            kp = jnp.zeros((hkv, 2, page_size, d), pool_dt)
            vp = jnp.zeros((hkv, 2, page_size, d), pool_dt)
            up = jnp.ones((3, hkv, d), pool_dt)
            idx = jnp.zeros((3,), jnp.int32)
            out = paged_kv_write_pallas(kp, vp, idx, idx, up, up)
            jax.block_until_ready(out)
            _KV_WRITE_PROBE[key] = True
        except Exception as exc:  # noqa: BLE001 — any lowering/runtime
            # failure routes every caller to the scatter path
            import logging

            logging.getLogger(__name__).warning(
                "pallas kv-write kernel unavailable for %s on %s (%s); "
                "falling back to XLA scatter", key, jax.default_backend(),
                str(exc)[:200])
            _KV_WRITE_PROBE[key] = False
    return _KV_WRITE_PROBE[key]


def paged_kv_write(k_pool, v_pool, write_page, write_off, k_upd, v_upd):
    """Dispatch: Pallas write kernel on TPU, XLA row scatter elsewhere.
    Override with POLYRL_KV_WRITE=scatter|pallas."""
    impl = os.environ.get("POLYRL_KV_WRITE", "")
    if impl != "scatter" and (
            impl == "pallas"
            or (jax.default_backend() == "tpu"
                and _pallas_kv_write_supported(
                    k_pool.shape[0], k_pool.shape[2], k_pool.shape[3],
                    k_pool.dtype, k_upd.dtype))):
        return paged_kv_write_pallas(
            k_pool, v_pool, write_page, write_off, k_upd, v_upd,
            interpret=jax.default_backend() != "tpu")
    from polyrl_tpu.models.decoder import _scatter_token_kv

    return (_scatter_token_kv(k_pool, write_page, write_off, k_upd),
            _scatter_token_kv(v_pool, write_page, write_off, v_upd))


def make_tp_paged_kv_write(mesh):
    """Tensor-parallel wrapper for the paged K/V write: pools and updates
    shard over tp on the KV-head dim (same split as the attention wrapper;
    GSPMD cannot partition the Pallas custom call, and an unsharded write
    would all-gather both pools per layer per step)."""
    from jax.sharding import PartitionSpec as P

    from polyrl_tpu.parallel.mesh import TP

    def inner(k_pool, v_pool, page, off, k_upd, v_upd):
        return paged_kv_write(k_pool, v_pool, page, off, k_upd, v_upd)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(TP, None, None, None), P(TP, None, None, None),
                  P(), P(), P(None, TP, None), P(None, TP, None)),
        out_specs=(P(TP, None, None, None), P(TP, None, None, None)),
        check_vma=False)


def make_tp_paged_attention(mesh):
    """Tensor-parallel wrapper: paged attention sharded over the tp axis on
    the HEAD dim (q [S, Hq, D] and both pools [Hkv, N, ps, D] split by tp;
    GQA query groups stay aligned with their shared KV head because both
    counts divide by tp). Needed because the Pallas kernel is a custom
    call — GSPMD cannot partition it, so without the shard_map a tp-sharded
    pool would be all-gathered per layer per step."""
    from jax.sharding import PartitionSpec as P

    from polyrl_tpu.parallel.mesh import TP

    def inner(q, k_pool, v_pool, page_table, seq_lens):
        return paged_attention(q, k_pool, v_pool, page_table, seq_lens)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(None, TP, None), P(TP, None, None, None),
                  P(TP, None, None, None), P(), P()),
        out_specs=P(None, TP, None), check_vma=False)


def paged_attention(q, k_pool, v_pool, page_table, seq_lens, scale=None):
    """Dispatch: the tuned library Pallas kernel on TPU, gather oracle
    elsewhere (interpret-mode for our custom kernel is exercised in tests;
    the oracle is faster for CPU test runs). Override with
    POLYRL_PAGED_ATTN=ref|pallas|lib."""
    impl = os.environ.get("POLYRL_PAGED_ATTN", "")
    if impl == "ref":
        return paged_attention_ref(q, k_pool, v_pool, page_table, seq_lens, scale)
    if impl == "pallas":
        return paged_attention_pallas(
            q, k_pool, v_pool, page_table, seq_lens, scale,
            interpret=jax.default_backend() != "tpu")
    if impl == "lib" or jax.default_backend() == "tpu":
        return paged_attention_lib(q, k_pool, v_pool, page_table, seq_lens, scale)
    return paged_attention_ref(q, k_pool, v_pool, page_table, seq_lens, scale)
