"""Core RL algorithms: advantage estimators, policy/value losses, KL penalties.

TPU-native reimplementation of the algorithmic surface the reference consumes
from verl's ``core_algos`` (see SURVEY.md §2.5; consumed at reference
``rlboost/verl_stream/workers/actor/stream_dp_actor.py:178-193`` and
``rlboost/verl_stream/workers/critic/stream_dp_critic.py:106-113``).

Everything here is a pure function on ``jnp`` arrays, jit-safe (static
shapes, no data-dependent Python control flow), and mask-aware: ``mask`` is
1.0 for response tokens and 0.0 for prompt/padding tokens. Shapes are
``[batch, seq]`` unless noted.
"""

from __future__ import annotations

import enum
from typing import Callable

import jax
import jax.numpy as jnp

_EPS = 1e-8


class AdvantageEstimator(str, enum.Enum):
    """Advantage estimators (reference enum at stream_ray_trainer.py:50,377,387)."""

    GAE = "gae"
    GRPO = "grpo"
    REINFORCE_PLUS_PLUS = "reinforce_plus_plus"
    REMAX = "remax"
    RLOO = "rloo"


# ---------------------------------------------------------------------------
# masked statistics helpers
# ---------------------------------------------------------------------------


def masked_sum(x: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    return jnp.sum(x * mask, axis=axis)


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    return masked_sum(x, mask, axis=axis) / (jnp.sum(mask, axis=axis) + _EPS)


def masked_var(x: jnp.ndarray, mask: jnp.ndarray, unbiased: bool = True) -> jnp.ndarray:
    mean = masked_mean(x, mask)
    var = masked_mean((x - mean) ** 2, mask)
    if unbiased:
        n = jnp.sum(mask)
        var = var * n / jnp.clip(n - 1.0, min=1.0)
    return var


def masked_whiten(x: jnp.ndarray, mask: jnp.ndarray, shift_mean: bool = True) -> jnp.ndarray:
    """Whiten ``x`` over masked entries (used before PPO policy loss with GAE)."""
    mean = masked_mean(x, mask)
    var = masked_var(x, mask)
    whitened = (x - mean) * jax.lax.rsqrt(var + _EPS)
    if not shift_mean:
        whitened = whitened + mean
    return whitened * mask


# ---------------------------------------------------------------------------
# advantage estimators
# ---------------------------------------------------------------------------


def compute_gae_advantage_return(
    token_level_rewards: jnp.ndarray,
    values: jnp.ndarray,
    response_mask: jnp.ndarray,
    gamma: float = 1.0,
    lam: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized Advantage Estimation over the response region.

    Returns ``(advantages, returns)``; advantages are whitened over the mask.
    Implemented as a reverse ``lax.scan`` over the time axis (TPU-friendly —
    no Python loop over sequence length).
    """
    seq_len = token_level_rewards.shape[-1]

    # next value: values shifted left; zeroed where the NEXT token is invalid
    # (i.e. no bootstrap past the last response token).
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros_like(values[:, :1])], axis=-1
    )
    next_mask = jnp.concatenate(
        [response_mask[:, 1:], jnp.zeros_like(response_mask[:, :1])], axis=-1
    )
    deltas = token_level_rewards + gamma * next_values * next_mask - values

    def backward_step(carry, xs):
        delta_t, mask_t = xs
        lastgaelam = delta_t + gamma * lam * carry
        # where masked, carry advantage through unchanged
        lastgaelam = jnp.where(mask_t > 0, lastgaelam, carry)
        return lastgaelam, lastgaelam

    init = jnp.zeros(token_level_rewards.shape[0], dtype=token_level_rewards.dtype)
    xs = (jnp.moveaxis(deltas, -1, 0)[::-1], jnp.moveaxis(response_mask, -1, 0)[::-1])
    _, advs_rev = jax.lax.scan(backward_step, init, xs)
    advantages = jnp.moveaxis(advs_rev[::-1], 0, -1)
    returns = advantages + values
    advantages = masked_whiten(advantages, response_mask)
    return advantages * response_mask, returns * response_mask


def compute_grpo_outcome_advantage(
    token_level_rewards: jnp.ndarray,
    response_mask: jnp.ndarray,
    group_ids: jnp.ndarray,
    norm_adv_by_std: bool = True,
    num_groups: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """GRPO outcome advantage: per-group reward z-score broadcast over tokens.

    ``group_ids`` is an int array [batch] mapping each trajectory to its
    prompt group (the reference unrolls ``n`` samples per prompt —
    sglang_rollout_remote.py:198-225). Implemented with segment sums so it
    stays jit-compatible for any grouping.
    """
    scores = masked_sum(token_level_rewards, response_mask, axis=-1)  # [B]
    if num_groups is None:
        num_groups = int(scores.shape[0])

    ones = jnp.ones_like(scores)
    group_count = jax.ops.segment_sum(ones, group_ids, num_segments=num_groups)
    group_sum = jax.ops.segment_sum(scores, group_ids, num_segments=num_groups)
    group_mean = group_sum / jnp.clip(group_count, min=1.0)
    centered = scores - group_mean[group_ids]
    if norm_adv_by_std:
        group_sqsum = jax.ops.segment_sum(centered**2, group_ids, num_segments=num_groups)
        group_std = jnp.sqrt(group_sqsum / jnp.clip(group_count - 1.0, min=1.0))
        centered = centered / (group_std[group_ids] + _EPS)
    advantages = centered[:, None] * response_mask
    return advantages, advantages


def compute_rloo_outcome_advantage(
    token_level_rewards: jnp.ndarray,
    response_mask: jnp.ndarray,
    group_ids: jnp.ndarray,
    num_groups: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """RLOO: leave-one-out baseline within each prompt group."""
    scores = masked_sum(token_level_rewards, response_mask, axis=-1)
    if num_groups is None:
        num_groups = int(scores.shape[0])
    ones = jnp.ones_like(scores)
    group_count = jax.ops.segment_sum(ones, group_ids, num_segments=num_groups)
    group_sum = jax.ops.segment_sum(scores, group_ids, num_segments=num_groups)
    n = group_count[group_ids]
    loo_baseline = (group_sum[group_ids] - scores) / jnp.clip(n - 1.0, min=1.0)
    adv = jnp.where(n > 1, scores - loo_baseline, scores)
    advantages = adv[:, None] * response_mask
    return advantages, advantages


def compute_reinforce_plus_plus_outcome_advantage(
    token_level_rewards: jnp.ndarray,
    response_mask: jnp.ndarray,
    gamma: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """REINFORCE++: discounted reward-to-go, globally whitened."""

    def backward_step(carry, xs):
        reward_t, mask_t = xs
        running = reward_t + gamma * carry
        running = jnp.where(mask_t > 0, running, carry)
        return running, running

    init = jnp.zeros(token_level_rewards.shape[0], dtype=token_level_rewards.dtype)
    xs = (
        jnp.moveaxis(token_level_rewards, -1, 0)[::-1],
        jnp.moveaxis(response_mask, -1, 0)[::-1],
    )
    _, ret_rev = jax.lax.scan(backward_step, init, xs)
    returns = jnp.moveaxis(ret_rev[::-1], 0, -1) * response_mask
    advantages = masked_whiten(returns, response_mask)
    return advantages * response_mask, returns


def compute_remax_outcome_advantage(
    token_level_rewards: jnp.ndarray,
    reward_baselines: jnp.ndarray,
    response_mask: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """ReMax: subtract the greedy-rollout baseline reward [batch]."""
    scores = masked_sum(token_level_rewards, response_mask, axis=-1)
    returns = (scores - reward_baselines)[:, None] * response_mask
    return returns, returns


# ---------------------------------------------------------------------------
# KL penalties (reference: verl core_algos.kl_penalty, applied at
# stream_ray_trainer.py:465-498 via apply_kl_penalty)
# ---------------------------------------------------------------------------


def kl_penalty(
    logprob: jnp.ndarray,
    ref_logprob: jnp.ndarray,
    penalty: str = "kl",
) -> jnp.ndarray:
    """Per-token KL penalty between policy and reference logprobs."""
    if penalty == "kl":
        return logprob - ref_logprob
    if penalty == "abs":
        return jnp.abs(logprob - ref_logprob)
    if penalty == "mse":
        return 0.5 * (logprob - ref_logprob) ** 2
    if penalty in ("low_var_kl", "k3"):
        # k3 estimator: exp(r) - r - 1 with r = ref - logprob; low variance,
        # non-negative. Clipped for numerical safety.
        kl = ref_logprob - logprob
        ratio = jnp.exp(jnp.clip(kl, -20.0, 20.0))
        return jnp.clip(ratio - kl - 1.0, -10.0, 10.0)
    raise NotImplementedError(f"unknown kl penalty: {penalty}")


def apply_kl_penalty(
    token_level_scores: jnp.ndarray,
    logprob: jnp.ndarray,
    ref_logprob: jnp.ndarray,
    response_mask: jnp.ndarray,
    kl_coef: float,
    penalty: str = "kl",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a KL penalty into token-level rewards; returns (rewards, mean_kl)."""
    kld = kl_penalty(logprob, ref_logprob, penalty) * response_mask
    token_level_rewards = token_level_scores - kl_coef * kld
    return token_level_rewards, masked_mean(kld, response_mask)


def truncated_importance_weights(
    old_log_probs: jnp.ndarray,
    rollout_log_probs: jnp.ndarray,
    response_mask: jnp.ndarray,
    cap: float = 2.0,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-token truncated importance-sampling (TIS) weights for off-policy
    rollouts (the pipelined trainer's one-version-stale generations; OPPO
    arxiv 2509.25762 / LlamaRL arxiv 2505.24034 both use this form):
    ``w = min(exp(old_lp - rollout_lp), cap)`` where ``old_lp`` is the
    CURRENT policy's logprob of the rollout token (recomputed at update
    time) and ``rollout_lp`` is the behavior policy's logprob captured at
    generation. Truncation at ``cap`` bounds the variance the reweighting
    can inject. Returns ``(weights, raw_ratio, mean_weight, clip_frac)``:
    ``weights`` are capped and zeroed outside the response mask;
    ``raw_ratio`` is the UNCAPPED per-token ratio so the training health
    ledger can histogram the off-policy disagreement (and where the clip
    bites) without a second exp/clip pass."""
    log_ratio = jnp.clip(old_log_probs - rollout_log_probs, -20.0, 20.0)
    ratio = jnp.exp(log_ratio)
    weights = jnp.minimum(ratio, cap) * response_mask
    mean_w = masked_mean(weights, response_mask)
    clip_frac = masked_mean((ratio > cap).astype(jnp.float32), response_mask)
    return weights, ratio, mean_w, clip_frac


def mixed_version_importance_weights(
    old_log_probs,
    rollout_log_probs,
    response_mask,
    weight_versions,
    current_version: int,
    cap: float = 2.0,
):
    """Mixed-version per-token truncated importance sampling for
    bounded-staleness async rollouts (``trainer.staleness_limit > 1``;
    ARCHITECTURE.md "Bounded-staleness async training").

    Generalizes :func:`truncated_importance_weights` from "one behavior
    policy per sequence" to sequences whose tokens were sampled under
    DIFFERENT weight versions: with pushes overlapping generation
    mid-stream, ``rollout_weight_versions`` records which push version
    sampled each token. The per-token ratio already keys off each token's
    own behavior-policy logprob (captured at sampling time under that
    token's version), so the correction itself stays
    ``min(exp(old_lp - rollout_lp), cap)``; what the version tensor adds:

    - the **exclusion set** — tokens whose version is unknown
      (``weight_versions == -1``: locally-finished degraded completions,
      pre-version-stamping engines) get weight 1.0 instead of a
      correction keyed to a behavior policy of unknown provenance, and
      are counted in ``stats["unknown_tokens"]`` (the
      ``training/tis_unknown_version_tokens`` gauge);
    - **per-version-lag clip statistics** — the off-policy disagreement
      and where the clip bites, bucketed by ``current_version − token
      version``, feeding the ``training/tis_{weight_mean,clip_frac}/
      lag<k>`` gauges next to the ``training/staleness`` ledger.

    Host-side numpy by design: the trainer calls this on host arrays the
    advantage pass already produced, and the per-lag bucketing is
    data-dependent (not jit-safe).

    Returns ``(weights, raw_ratio, stats)``: ``weights`` are capped,
    1.0 on unknown-version tokens, zeroed outside the mask; ``raw_ratio``
    is the uncapped per-token ratio (unmasked); ``stats`` carries
    ``mean_weight`` (over masked tokens — the applied correction),
    ``clip_frac`` (clipped / known-version tokens), ``known_tokens``,
    ``unknown_tokens``, ``max_lag``, and ``per_lag`` as
    ``{lag: {"tokens", "weight_sum", "clipped"}}`` raw sums so per-step
    accumulation stays exact (obs/rlhealth.py aggregates them)."""
    import numpy as np

    old = np.asarray(old_log_probs, np.float32)
    beh = np.asarray(rollout_log_probs, np.float32)
    mask = np.asarray(response_mask) > 0
    if weight_versions is None:
        wv = np.full(old.shape, -1, np.int32)
    else:
        wv = np.asarray(weight_versions, np.int32)
    ratio = np.exp(np.clip(old - beh, -20.0, 20.0)).astype(np.float32)
    known = mask & (wv >= 0)
    unknown = mask & (wv < 0)
    weights = np.where(known, np.minimum(ratio, np.float32(cap)),
                       np.float32(0.0)).astype(np.float32)
    weights[unknown] = 1.0
    clipped = known & (ratio > cap)
    n_known = int(known.sum())
    n_mask = int(mask.sum())
    per_lag: dict[int, dict] = {}
    max_lag = 0
    if n_known:
        lags = np.maximum(int(current_version) - wv, 0)
        for lag in np.unique(lags[known]):
            sel = known & (lags == lag)
            per_lag[int(lag)] = {
                "tokens": int(sel.sum()),
                "weight_sum": float(weights[sel].sum()),
                "clipped": int(clipped[sel].sum()),
            }
        max_lag = int(lags[known].max())
    stats = {
        "mean_weight": float(weights[mask].mean()) if n_mask else 1.0,
        "clip_frac": float(clipped.sum()) / n_known if n_known else 0.0,
        "known_tokens": n_known,
        "unknown_tokens": int(unknown.sum()),
        "max_lag": max_lag,
        "per_lag": per_lag,
    }
    return weights, ratio, stats


# ---------------------------------------------------------------------------
# loss aggregation (verl agg_loss; consumed at stream_dp_actor.py:178-193)
# ---------------------------------------------------------------------------


def agg_loss(
    loss_mat: jnp.ndarray,
    loss_mask: jnp.ndarray,
    loss_agg_mode: str = "token-mean",
) -> jnp.ndarray:
    """Aggregate a [B, T] per-token loss into a scalar."""
    if loss_agg_mode == "token-mean":
        return masked_mean(loss_mat, loss_mask)
    if loss_agg_mode == "seq-mean-token-sum":
        seq_losses = masked_sum(loss_mat, loss_mask, axis=-1)
        return jnp.mean(seq_losses)
    if loss_agg_mode == "seq-mean-token-mean":
        seq_losses = masked_mean(loss_mat, loss_mask, axis=-1)
        return jnp.mean(seq_losses)
    if loss_agg_mode == "seq-mean-token-sum-norm":
        seq_losses = masked_sum(loss_mat, loss_mask, axis=-1)
        return jnp.sum(seq_losses) / loss_mask.shape[-1]
    raise NotImplementedError(f"unknown loss_agg_mode: {loss_agg_mode}")


# ---------------------------------------------------------------------------
# policy losses (vanilla / gpg / clip_cov — reference dispatch at
# stream_dp_actor.py:178-182 via get_policy_loss_fn)
# ---------------------------------------------------------------------------


def compute_policy_loss_vanilla(
    old_log_prob: jnp.ndarray,
    log_prob: jnp.ndarray,
    advantages: jnp.ndarray,
    response_mask: jnp.ndarray,
    clip_ratio: float = 0.2,
    clip_ratio_low: float | None = None,
    clip_ratio_high: float | None = None,
    clip_ratio_c: float = 3.0,
    loss_agg_mode: str = "token-mean",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """PPO clipped surrogate with dual-clip.

    Returns (loss, clipfrac, approx_kl, clipfrac_lower).
    """
    lo = clip_ratio_low if clip_ratio_low is not None else clip_ratio
    hi = clip_ratio_high if clip_ratio_high is not None else clip_ratio

    negative_approx_kl = jnp.clip(log_prob - old_log_prob, -20.0, 20.0)
    ratio = jnp.exp(negative_approx_kl)
    approx_kl = masked_mean(-negative_approx_kl, response_mask)

    pg_losses1 = -advantages * ratio
    pg_losses2 = -advantages * jnp.clip(ratio, 1.0 - lo, 1.0 + hi)
    clip_pg_losses1 = jnp.maximum(pg_losses1, pg_losses2)
    clipfrac = masked_mean((pg_losses2 > pg_losses1).astype(jnp.float32), response_mask)

    # dual-clip: bound the loss when advantage < 0 and ratio explodes
    pg_losses3 = -advantages * clip_ratio_c
    clip_pg_losses2 = jnp.minimum(pg_losses3, clip_pg_losses1)
    clipfrac_lower = masked_mean(
        ((clip_pg_losses1 > pg_losses3) & (advantages < 0)).astype(jnp.float32),
        response_mask,
    )
    pg_losses = jnp.where(advantages < 0, clip_pg_losses2, clip_pg_losses1)
    pg_loss = agg_loss(pg_losses, response_mask, loss_agg_mode)
    return pg_loss, clipfrac, approx_kl, clipfrac_lower


def compute_policy_loss_gpg(
    old_log_prob: jnp.ndarray,
    log_prob: jnp.ndarray,
    advantages: jnp.ndarray,
    response_mask: jnp.ndarray,
    loss_agg_mode: str = "token-mean",
    **_: object,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """GPG: plain policy-gradient loss (no ratio, no clip)."""
    pg_losses = -log_prob * advantages
    pg_loss = agg_loss(pg_losses, response_mask, loss_agg_mode)
    zero = jnp.zeros((), dtype=pg_loss.dtype)
    return pg_loss, zero, zero, zero


def compute_policy_loss_clip_cov(
    old_log_prob: jnp.ndarray,
    log_prob: jnp.ndarray,
    advantages: jnp.ndarray,
    response_mask: jnp.ndarray,
    clip_ratio: float = 0.2,
    clip_ratio_low: float | None = None,
    clip_ratio_high: float | None = None,
    clip_cov_ratio: float = 0.0002,
    clip_cov_lb: float = 1.0,
    clip_cov_ub: float = 5.0,
    loss_agg_mode: str = "token-mean",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Clip-Cov: unclip the highest-covariance tokens to keep exploration.

    Tokens whose covariance cov(logp, A) falls within [lb, ub] are candidates
    for clipping exemption; the top ``clip_cov_ratio`` fraction by covariance
    is exempted from the PPO clip. jit-safe via a static top-k size.
    """
    lo = clip_ratio_low if clip_ratio_low is not None else clip_ratio
    hi = clip_ratio_high if clip_ratio_high is not None else clip_ratio

    negative_approx_kl = jnp.clip(log_prob - old_log_prob, -20.0, 20.0)
    ratio = jnp.exp(negative_approx_kl)
    approx_kl = masked_mean(-negative_approx_kl, response_mask)

    pg_losses1 = -advantages * ratio
    pg_losses2 = -advantages * jnp.clip(ratio, 1.0 - lo, 1.0 + hi)

    corr = jnp.ones_like(advantages)
    centered_lp = log_prob - masked_mean(log_prob, response_mask)
    centered_adv = advantages - masked_mean(advantages, response_mask)
    cov = centered_lp * centered_adv
    cov = jnp.where(response_mask > 0, cov, -jnp.inf)
    in_band = (cov >= clip_cov_lb) & (cov <= clip_cov_ub)

    n_tokens = advantages.shape[0] * advantages.shape[1]
    k = max(int(n_tokens * clip_cov_ratio), 1)
    flat_cov = jnp.where(in_band.reshape(-1), cov.reshape(-1), -jnp.inf)
    _, topk_idx = jax.lax.top_k(flat_cov, k)
    corr = corr.reshape(-1).at[topk_idx].set(0.0).reshape(advantages.shape)
    # only exempt where cov was finite (top_k may select -inf when few valid)
    corr = jnp.where(jnp.isfinite(flat_cov.reshape(advantages.shape)), corr, 1.0)

    clipped = (pg_losses2 > pg_losses1).astype(jnp.float32) * corr
    clipfrac = masked_mean(clipped, response_mask)
    pg_losses = jnp.maximum(pg_losses1, pg_losses2) * corr + pg_losses1 * (1.0 - corr)
    pg_loss = agg_loss(pg_losses, response_mask, loss_agg_mode)
    return pg_loss, clipfrac, approx_kl, jnp.zeros_like(clipfrac)


POLICY_LOSS_FNS: dict[str, Callable] = {
    "vanilla": compute_policy_loss_vanilla,
    "gpg": compute_policy_loss_gpg,
    "clip_cov": compute_policy_loss_clip_cov,
}


def get_policy_loss_fn(name: str = "vanilla") -> Callable:
    """Policy-loss dispatch (reference: stream_dp_actor.py:178-182)."""
    try:
        return POLICY_LOSS_FNS[name]
    except KeyError:
        raise NotImplementedError(f"unknown policy loss: {name}") from None


# ---------------------------------------------------------------------------
# value loss (verl compute_value_loss; consumed at stream_dp_critic.py:106)
# ---------------------------------------------------------------------------


def compute_value_loss(
    vpreds: jnp.ndarray,
    returns: jnp.ndarray,
    values: jnp.ndarray,
    response_mask: jnp.ndarray,
    cliprange_value: float = 0.5,
    loss_agg_mode: str = "token-mean",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Clipped value loss; returns (loss, clipfrac)."""
    vpredclipped = jnp.clip(vpreds, values - cliprange_value, values + cliprange_value)
    vf_losses1 = (vpreds - returns) ** 2
    vf_losses2 = (vpredclipped - returns) ** 2
    clipped = jnp.maximum(vf_losses1, vf_losses2)
    vf_loss = 0.5 * agg_loss(clipped, response_mask, loss_agg_mode)
    vf_clipfrac = masked_mean((vf_losses2 > vf_losses1).astype(jnp.float32), response_mask)
    return vf_loss, vf_clipfrac


def entropy_from_logits(logits: jnp.ndarray) -> jnp.ndarray:
    """Token-level entropy of a categorical distribution from raw logits."""
    logz = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    logp = logits - logz
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def logprobs_from_logits(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-token logprob of ``labels`` under ``logits`` ([..., V] → [...])."""
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return label_logits - logz
