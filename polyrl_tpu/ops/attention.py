"""Attention ops: dense reference implementation + dispatch point for Pallas.

The reference's training attention is flash-attn varlen (SURVEY.md §2.2,
``stream_dp_actor.py:41-43``) and its rollout attention is SGLang
RadixAttention/paged-KV CUDA kernels. Here the contract is a single
``attention`` entry: a dense, mask-based implementation that XLA fuses well
at v0, with the same signature later served by Pallas splash/ragged kernels
(see polyrl_tpu/ops/pallas/).

Shapes follow TPU-friendly layout [B, T, H, D] (batch, seq, heads, head_dim)
— contraction dims land on the MXU lanes, and the seq dim stays shardable
along the ``sp`` mesh axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """GQA: repeat KV heads to match Q heads. [B, T, Hkv, D] → [B, T, Hkv*n, D]."""
    if n_rep == 1:
        return x
    b, t, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, t, h, n_rep, d)).reshape(b, t, h * n_rep, d)


def causal_mask(q_len: int, kv_len: int, q_offset: jnp.ndarray | int = 0) -> jnp.ndarray:
    """[q_len, kv_len] boolean mask; True = attend. q position i sits at
    absolute position q_offset + i."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return kv_pos <= q_pos


def attention(
    q: jnp.ndarray,  # [B, Tq, Hq, D]
    k: jnp.ndarray,  # [B, Tk, Hkv, D]
    v: jnp.ndarray,  # [B, Tk, Hkv, D]
    mask: jnp.ndarray | None = None,  # broadcastable to [B, Hq, Tq, Tk]; True=attend
    scale: float | None = None,
    logits_dtype: jnp.dtype = jnp.float32,
) -> jnp.ndarray:
    """Dense scaled-dot-product attention with GQA.

    Softmax runs in float32 (MXU accumulates f32 anyway; keeps logprob math
    trustworthy for token-level continuation — SURVEY.md §7 hard part #1).
    """
    b, tq, hq, d = q.shape
    hkv = k.shape[2]
    scale = scale if scale is not None else d ** -0.5

    if hq != hkv:
        # grouped einsum — contracting against the shared KV head directly.
        # Materializing repeat_kv costs 2·B·Tk·Hq·D bytes of HBM traffic per
        # call; at decode (Tq=1, called per layer per step) that expansion
        # dominated the whole step (~0.2 ms/layer at B=64, S=256).
        g = hq // hkv
        qg = q.reshape(b, tq, hkv, g, d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                            preferred_element_type=logits_dtype)
        logits = logits * scale
        if mask is not None:
            logits = jnp.where(mask[:, :, None] if mask.ndim == 4 else mask,
                               logits, jnp.finfo(logits_dtype).min)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
        return out.reshape(b, tq, hq, d)

    # [B, H, Tq, Tk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=logits_dtype)
    logits = logits * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits_dtype).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out
