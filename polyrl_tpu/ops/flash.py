"""Flash attention for the TRAINING path (fwd+bwd, O(T) memory).

The reference trains with flash-attn varlen CUDA kernels
(``stream_dp_actor.py:41-43``, SURVEY.md §2.2 row 2); the TPU equivalent is
blockwise attention with an online softmax. We use JAX's bundled Pallas TPU
flash kernel (``jax.experimental.pallas.ops.tpu.flash_attention`` — public
JAX API with a custom VJP) behind a wrapper that:

- takes this codebase's [B, T, H, D] layout and a [B, T] validity mask,
- expresses padding through segment ids (pad=0, real=1 — pads only attend
  pads, which the loss masks out; packed sequences pass their own ids),
- handles GQA by repeating KV heads to the query head count,
- falls back to the dense masked implementation off-TPU or when the
  sequence length doesn't tile (Pallas blocks must divide T).

Without this, dense logits [B, H, T, T] f32 cap training at short T — the
reference recipe's 14336-token responses are unreachable (a single head row
at T=15360 is 900 MB).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from polyrl_tpu.ops.attention import attention, causal_mask

_BLOCKS = (1024, 512, 256, 128)


def _pick_block(t: int) -> int | None:
    for b in _BLOCKS:
        if t % b == 0:
            return b
    return None


def supports_flash(t: int, head_dim: int) -> bool:
    return (jax.default_backend() == "tpu"
            and _pick_block(t) is not None
            and head_dim % 128 == 0)


def _dense(q, k, v, attn_mask, causal: bool, segment_ids=None):
    t = q.shape[1]
    if segment_ids is not None:
        # packed sequences: tokens attend only within their own segment
        # (block-diagonal), matching the Pallas kernel's SegmentIds semantics
        mask = (segment_ids[:, None, :, None] == segment_ids[:, None, None, :])
        mask = mask & (attn_mask[:, None, None, :] > 0)
    else:
        mask = attn_mask[:, None, None, :] > 0
    if causal:
        mask = causal_mask(t, t)[None, None] & mask
    return attention(q, k, v, mask=mask)


def flash_attention_train(q, k, v, attn_mask, *, causal: bool = True,
                          segment_ids=None):
    """q [B,T,Hq,D], k/v [B,T,Hkv,D], attn_mask [B,T] (1=valid). Returns
    [B,T,Hq,D]. ``segment_ids`` [B,T] overrides the mask-derived ids for
    packed-sequence training."""
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    if not supports_flash(t, d):
        return _dense(q, k, v, attn_mask, causal, segment_ids)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, SegmentIds, flash_attention)

    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    ids = (segment_ids if segment_ids is not None
           else attn_mask.astype(jnp.int32))
    blk = _pick_block(t)
    bs = BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk,
        block_k_dkv=blk, block_q_dkv=blk,
        block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk,
    )
    out = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        segment_ids=SegmentIds(q=ids, kv=ids),
        causal=causal, sm_scale=d ** -0.5, block_sizes=bs)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def auto_train_attention():
    """attn_fn for ``decoder.forward``'s no-cache path: flash on TPU, dense
    masked attention elsewhere. Signature: (q, k, v, attn_mask)."""
    return functools.partial(flash_attention_train, causal=True)
