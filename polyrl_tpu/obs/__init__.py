"""Observability subsystem: span tracing + histogram metrics + scraping.

The pieces (ARCHITECTURE.md "Observability"):

- :mod:`polyrl_tpu.obs.trace` — ``Span``/``Tracer`` with thread-local
  context, a bounded ring buffer, and Chrome-trace/Perfetto JSON export.
  Cross-process propagation rides ``X-Trace-Id``/``X-Span-Id`` HTTP headers
  (ManagerClient → C++ manager → rollout server) so one rollout request can
  be followed trainer→manager→engine in a single Perfetto timeline.
- :mod:`polyrl_tpu.obs.histogram` — fixed-bucket log2 ``Histogram``
  (p50/p95/p99/max) plus a process-global registry any component can
  ``observe()`` into; the trainer drains it into each step record.
- :mod:`polyrl_tpu.obs.scrape` — Prometheus text-exposition parser for the
  manager's ``GET /metrics``, merged into step records as ``manager/*``.
- :mod:`polyrl_tpu.obs.goodput` — per-step wall-time attribution ledger
  (``goodput/*`` phase metrics, tokens/chip/s, MFU estimate).
- :mod:`polyrl_tpu.obs.statusz` — the live ``/statusz`` health plane: one
  JSON schema served by both the trainer and the rollout server.
- :mod:`polyrl_tpu.obs.recorder` — anomaly flight recorder: EWMA/z-score
  detection (per-key direction-aware) over the step stream + post-mortem
  bundle dumps.
- :mod:`polyrl_tpu.obs.rlhealth` — training health plane: per-step
  RL-dynamics ledger (advantage/TIS/staleness distributions, GRPO group
  diagnostics) behind the ``training/*`` namespace, the /statusz
  ``training`` section, and ``training.json`` post-mortem bundles.
- :mod:`polyrl_tpu.obs.critical_path` — per-step critical-path
  extraction over the span ring: which chain of spans actually bounded
  the step (``critpath/*`` gauges, ``critical_path.json`` bundles).
- :mod:`polyrl_tpu.obs.timeseries` — fleet time-series rail: bounded
  per-key rings of step snapshots with windowed aggregates + slopes (the
  /statusz ``timeseries`` section, the autoscaling trend input).

Everything here is import-light (no jax at module load) and no-op-cheap
when tracing is disabled, so hot paths can call into it unconditionally.
"""

from __future__ import annotations

import contextlib

from polyrl_tpu.obs.critical_path import (SEGMENTS,  # noqa: F401
                                          CriticalPath,
                                          extract_critical_path)
from polyrl_tpu.obs.goodput import GoodputLedger  # noqa: F401
from polyrl_tpu.obs.histogram import (Histogram, drain_histograms,  # noqa: F401
                                      observe)
from polyrl_tpu.obs.recorder import (AnomalyDetector,  # noqa: F401
                                     FlightRecorder, direction_violates)
from polyrl_tpu.obs.rlhealth import TrainingHealthLedger  # noqa: F401
from polyrl_tpu.obs.scrape import (manager_gauges,  # noqa: F401
                                   manager_gauges_partial,
                                   parse_prometheus_text,
                                   parse_prometheus_text_partial)
from polyrl_tpu.obs.statusz import StatuszServer, build_snapshot  # noqa: F401
from polyrl_tpu.obs.timeseries import (TimeSeriesStore,  # noqa: F401
                                       least_squares_slope)
from polyrl_tpu.obs.trace import Tracer, get_tracer  # noqa: F401

_jax_annotations = False


def configure(trace: bool | None = None, max_spans: int | None = None,
              out_dir: str | None = None,
              jax_annotations: bool | None = None,
              reset: bool = False) -> Tracer:
    """Configure the process-global tracer (and the jax-annotation toggle).
    ``None`` leaves a setting unchanged; ``reset`` clears the span ring
    buffer and the histogram registry (test isolation / fresh runs)."""
    global _jax_annotations
    tracer = get_tracer()
    if trace is not None:
        tracer.enabled = trace
    if max_spans is not None:
        tracer.set_capacity(max_spans)
    if out_dir is not None:
        tracer.out_dir = out_dir or None
    if jax_annotations is not None:
        _jax_annotations = jax_annotations
    if reset:
        tracer.clear()
        drain_histograms()
    return tracer


def span(name: str, **attrs):
    """Open a span on the global tracer (no-op when tracing is disabled)."""
    return get_tracer().span(name, **attrs)


def trace_headers() -> dict[str, str]:
    """HTTP headers carrying the current trace context ({} when none)."""
    return get_tracer().headers()


def phase_annotation(name: str):
    """Optional ``jax.profiler.TraceAnnotation`` so device traces line up
    with host spans (configure(jax_annotations=True)); nullcontext
    otherwise — jax is only imported when the feature is on."""
    if not _jax_annotations:
        return contextlib.nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — annotation is best-effort
        return contextlib.nullcontext()
