"""Fleet time-series rail: bounded per-key rings of step snapshots with
windowed aggregates (ARCHITECTURE.md "Critical-path & time-series plane").

The live planes so far expose LAST-step scalars (/statusz gauges, the
step record) — enough to answer "what is it doing now", useless for
"which way is it trending". The balance-driven autoscaling the ROADMAP
targets (Adaptive Placement in PAPERS.md) needs trend signals: is fleet
occupancy climbing toward saturation, is the trainer bubble shrinking
after an engine join, is decode throughput sagging. This module is that
rail: a :class:`TimeSeriesStore` keeps a bounded ``deque`` of
``(step, value)`` points per metric key (filtered by namespace prefix so
an unbounded key set can't grow the store) and renders windowed
aggregates — mean/p95/min/max plus a least-squares **slope** per step —
into the ``timeseries`` section of the ``polyrl/statusz/v4`` schema on
both planes, ``BalanceEstimator.trends()``, and tools/fleet_report.py.

Import-light (stdlib only) and cheap per observe: one deque append per
tracked key; aggregates are computed lazily at snapshot time.
"""

from __future__ import annotations

import threading
from collections import deque

# step-record namespaces the rail tracks by default: the goodput phase
# walls, the critical-path attribution, perf/pool/engine/training gauges
# — everything the autoscaling loop or a trend dashboard would window
DEFAULT_PREFIXES = ("goodput/", "perf/", "pool/", "engine/", "training/",
                    "manager/", "critpath/", "autoscale/")


def least_squares_slope(xs, ys) -> float:
    """Ordinary least-squares slope of ``ys`` over ``xs`` (0.0 for fewer
    than two points or a degenerate x-range)."""
    xs = [float(x) for x in xs]
    ys = [float(y) for y in ys]
    n = len(xs)
    if n < 2 or len(ys) != n:
        return 0.0
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0.0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom


def _p95(sorted_vals: list[float]) -> float:
    """p95 by the nearest-rank method over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    rank = max(0, min(len(sorted_vals) - 1,
                      int(round(0.95 * (len(sorted_vals) - 1)))))
    return sorted_vals[rank]


def aggregate(points: list[tuple[float, float]]) -> dict[str, float]:
    """Windowed summary of ``(step, value)`` points: last/mean/p95/min/
    max/count plus the least-squares slope PER STEP (so a counter that
    climbs by 1 each step reads slope=1.0 regardless of window size)."""
    if not points:
        return {"count": 0}
    vals = [v for _, v in points]
    srt = sorted(vals)
    return {
        "last": vals[-1],
        "mean": sum(vals) / len(vals),
        "p95": _p95(srt),
        "min": srt[0],
        "max": srt[-1],
        "slope": least_squares_slope([s for s, _ in points], vals),
        "count": len(vals),
    }


class TimeSeriesStore:
    """Bounded per-key ring of step snapshots.

    ``observe(step, record)`` folds one step's metric record in, keeping
    only numeric values under the tracked ``prefixes``; each key holds at
    most ``capacity`` points and the store at most ``max_keys`` keys
    (first-seen wins — a runaway per-instance key family can't evict the
    core series). Thread-safe: the statusz exporter snapshots from its
    HTTP thread while the fit loop observes.
    """

    def __init__(self, capacity: int = 256, max_keys: int = 512,
                 prefixes: tuple[str, ...] = DEFAULT_PREFIXES):
        self.capacity = max(2, int(capacity))
        self.max_keys = max(1, int(max_keys))
        self.prefixes = tuple(prefixes)
        self.dropped_keys = 0
        self._series: dict[str, deque] = {}
        self._lock = threading.Lock()

    def tracks(self, key: str) -> bool:
        return key.startswith(self.prefixes)

    def observe(self, step: float, record: dict) -> None:
        """Fold one step's record in (keys not under a tracked prefix, and
        non-numeric/bool values, are skipped)."""
        step = float(step)
        with self._lock:
            for key, value in record.items():
                if not isinstance(key, str) or not self.tracks(key):
                    continue
                if isinstance(value, bool) or \
                        not isinstance(value, (int, float)):
                    continue
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= self.max_keys:
                        self.dropped_keys += 1
                        continue
                    ring = self._series[key] = deque(maxlen=self.capacity)
                ring.append((step, float(value)))

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, key: str, window: int = 0) -> list[tuple[float, float]]:
        """The ``(step, value)`` points of ``key`` (last ``window`` when
        > 0); [] for an untracked key."""
        with self._lock:
            pts = list(self._series.get(key, ()))
        return pts[-window:] if window > 0 else pts

    def aggregates(self, key: str, window: int = 0) -> dict[str, float]:
        return aggregate(self.series(key, window))

    def section(self, window: int = 32) -> dict:
        """The /statusz ``timeseries`` section: per-key windowed aggregates
        plus the store's own shape, so a fleet sweep can window-compare
        slopes without shipping raw points."""
        with self._lock:
            items = [(k, list(r)) for k, r in self._series.items()]
        return {
            "window": int(window),
            "capacity": self.capacity,
            "tracked_keys": len(items),
            "dropped_keys": self.dropped_keys,
            "keys": {
                k: {name: (round(v, 6) if isinstance(v, float) else v)
                    for name, v in
                    aggregate(pts[-window:] if window > 0 else pts).items()}
                for k, pts in sorted(items)},
        }
