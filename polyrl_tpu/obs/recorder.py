"""Anomaly flight recorder (ARCHITECTURE.md "Goodput & health plane").

BENCH_r01–r05 all died rc=124 with nobody noticing mid-run: nothing was
watching the live trajectory. The recorder watches the per-step record
stream with an EWMA/z-score detector over step time and decode throughput
and, on anomaly, crash, or SIGTERM, dumps a self-contained post-mortem
bundle into the run directory:

``<out_dir>/postmortem/<seq>-<reason>/``
    ``spans.jsonl``    — the tracer ring buffer (the last trace_buffer
                         spans across trainer/manager/engine)
    ``steps.jsonl``    — the last ``keep_steps`` step records
    ``stacks.txt``     — ``faulthandler`` dump of every thread's stack
    ``counters.json``  — reason, anomaly details, fault/salvage counters,
                         detector state
    ``engine.json``    — fleet flight-deck view (``engine_fn``; when wired)
    ``training.json``  — training health ledger tail + last batch's GRPO
                         group table (``training_fn``; when wired)
    ``critical_path.json`` — the last N per-step critical paths
                         (obs/critical_path.py via ``critical_path_fn``;
                         when wired) — the bundle answers "what chain
                         bounded the steps before this died"
    ``memory.json``    — the KV memory plane view (rollout/kvledger.py via
                         ``memory_fn``; when wired) — page roles, residency
                         tiers, free-cause churn and the ledger↔pool
                         reconciliation at anomaly time
    ``engine_profile.json`` — the fleet engine-loop profiler view
                         (obs/engine_profile.py via ``engine_profile_fn``;
                         when wired) — per-engine device-vs-host wall
                         split at anomaly time
    ``memprof.pprof``  — best-effort ``jax.profiler.device_memory_profile``
                         snapshot (real devices only; silently skipped on
                         CPU or when jax is absent)

Detector design: EWMA mean + EW variance with a **median-initialized
warmup** (the first step carries jit compiles — seeding the mean from the
median of the warmup window keeps one cold-start outlier from poisoning
the baseline) and a sigma floor (``min_sigma_frac`` of the mean) so a
near-constant series doesn't hair-trigger on noise. Anomalous samples are
NOT folded into the statistics — one stall yields one anomaly, and the
recovered steps after it read normal again (pinned by test).
"""

from __future__ import annotations

import collections
import faulthandler
import json
import logging
import math
import os
import re
import signal
import threading
import time

log = logging.getLogger(__name__)


DIRECTIONS = ("low", "high", "both")


def direction_violates(direction: str, excursion: float) -> bool:
    """Shared per-key direction semantics — the FlightRecorder watch and
    ``tools/bench_gate.py`` both decide "is this move in the BAD
    direction" here instead of duplicating it. ``excursion`` is any
    signed deviation from the baseline (a z-score, a ratio minus 1):
    ``'high'`` fires on positive excursions (KL blowing up, a latency
    rising), ``'low'`` on negative ones (entropy collapsing, throughput
    dropping), ``'both'`` on either."""
    if direction == "high":
        return excursion > 0.0
    if direction == "low":
        return excursion < 0.0
    if direction == "both":
        return excursion != 0.0
    raise ValueError(f"direction must be one of {DIRECTIONS}, "
                     f"got {direction!r}")


class AnomalyDetector:
    """EWMA/z-score detector for one metric stream. ``direction`` gates
    which excursions COUNT as anomalous: a symmetric detector over
    ``training/entropy`` would fire on a healthy entropy rise exactly as
    on a collapse — only the watched direction fires. Extreme samples in
    the healthy direction still don't fold into the statistics (they are
    outliers either way; the baseline must survive them)."""

    def __init__(self, z_threshold: float = 4.0, warmup: int = 5,
                 alpha: float = 0.3, min_sigma_frac: float = 0.1,
                 direction: str = "both"):
        if warmup < 2:
            raise ValueError(f"warmup must be >= 2, got {warmup}")
        if direction not in DIRECTIONS:
            raise ValueError(f"direction must be one of {DIRECTIONS}, "
                             f"got {direction!r}")
        self.z_threshold = z_threshold
        self.warmup = warmup
        self.alpha = alpha
        self.min_sigma_frac = min_sigma_frac
        self.direction = direction
        self.mean: float | None = None
        self.var = 0.0
        self.n = 0
        self._warm: list[float] = []

    def _sigma(self) -> float:
        # floor: EW sigma, but never below min_sigma_frac of |mean| — a
        # perfectly steady warmup must not make ordinary jitter anomalous
        return max(math.sqrt(self.var),
                   self.min_sigma_frac * abs(self.mean or 0.0), 1e-12)

    def observe(self, value: float) -> float | None:
        """Feed one sample; returns its z-score when anomalous, else None.
        Warmup samples are never anomalous; anomalous samples do not
        update the statistics."""
        v = float(value)
        self.n += 1
        if self.mean is None:
            self._warm.append(v)
            if len(self._warm) >= self.warmup:
                # median-initialized baseline: robust to the cold-start
                # outlier (first-step jit compiles) inside the warmup
                srt = sorted(self._warm)
                mid = len(srt) // 2
                med = (srt[mid] if len(srt) % 2
                       else 0.5 * (srt[mid - 1] + srt[mid]))
                self.mean = med
                dev = sorted(abs(x - med) for x in srt)
                mad = (dev[mid] if len(dev) % 2
                       else 0.5 * (dev[mid - 1] + dev[mid]))
                # 1.4826 ~ MAD->sigma for a normal distribution
                self.var = (1.4826 * mad) ** 2
                self._warm = []
            return None
        z = (v - self.mean) / self._sigma()
        if abs(z) > self.z_threshold:
            # extreme either way: never folded into the baseline; only
            # the watched direction is REPORTED as anomalous
            return z if direction_violates(self.direction, z) else None
        a = self.alpha
        delta = v - self.mean
        self.mean += a * delta
        self.var = (1 - a) * (self.var + a * delta * delta)
        return None

    def state(self) -> dict:
        return {"n": self.n, "mean": self.mean, "sigma": self._sigma()
                if self.mean is not None else None,
                "direction": self.direction,
                "warmed": self.mean is not None}


# step-record keys the recorder watches by default, each with the
# direction that IS the anomaly: wall step time (a stall spikes it), the
# rollout plane's decode throughput (a sick pool collapses it), and the
# fleet flight-deck gauges (PoolManager.counters) keep their original
# symmetric watch; the training health plane (obs/rlhealth.py) is
# direction-aware — entropy collapsing DOWN and KL / grad norm /
# degenerate-group fraction blowing UP are the anomalies, their healthy
# moves are not. Keys absent from the step record (no pool attached, no
# health ledger) are simply never fed.
DEFAULT_WATCH = {
    "perf/step_time_s": "both",
    "perf/rollout_throughput_tok_s": "both",
    "engine/occupancy": "both",
    "engine/page_util": "both",
    "training/entropy": "low",
    "training/approx_kl": "high",
    "training/grad_norm": "high",
    "training/degenerate_group_frac": "high",
    # weight-fabric supervision (transfer/agents.py): a cumulative failed-
    # push counter starting to climb means the sync fabric is degrading —
    # only a RISE is the anomaly
    "transfer/push_failures": "high",
    # degradation-tier ladder (rollout/autoscale.py): 0 remote-preferred,
    # 1 colocated fallback, 2 local degraded completion — climbing UP the
    # ladder is the anomaly, recovering back down is healthy
    "autoscale/degrade_tier": "high",
    # KV memory plane (rollout/kvledger.py): the resident set going COLD
    # (pages nobody touches accumulating) is the anomaly — a busy cache
    # keeps its pages warm; HBM headroom only matters when it DROPS
    "engine/kv_cold_page_frac": "high",
    "engine/hbm_headroom_gb": "low",
    # host-RAM spill tier (rollout/kvspill.py): a climbing restore rate
    # means pages are thrashing between host and HBM — spilled pages being
    # pulled straight back means the watermarks are fighting the workload
    "engine/kv_restore_rate": "high",
    # engine-loop profiler (obs/engine_profile.py): device_frac DROPPING
    # means an engine's loop thread stopped feeding the chip (host-bound
    # regression); accounting_frac RISING means the deck/ledger/spill
    # bookkeeping started eating the loop — both one-sided
    "engine/device_frac": "low",
    "engine/accounting_frac": "high",
}


def _normalize_watch(watch) -> dict[str, str]:
    """Watch spec → ``{key: direction}``: a mapping passes through; an
    iterable accepts bare keys (symmetric watch, the pre-direction
    behavior) or ``(key, direction)`` pairs."""
    if isinstance(watch, dict):
        return dict(watch)
    out: dict[str, str] = {}
    for item in watch:
        if isinstance(item, str):
            out[item] = "both"
        else:
            key, direction = item
            out[key] = direction
    return out


class FlightRecorder:
    """Watches the step-record stream; dumps post-mortem bundles."""

    def __init__(self, out_dir: str, keep_steps: int = 64,
                 z_threshold: float = 4.0, warmup: int = 5,
                 alpha: float = 0.3, min_sigma_frac: float = 0.1,
                 max_bundles: int = 4,
                 watch=DEFAULT_WATCH):
        self.out_dir = out_dir
        self.max_bundles = max_bundles
        self._steps: collections.deque = collections.deque(maxlen=keep_steps)
        self._detectors = {
            key: AnomalyDetector(z_threshold=z_threshold, warmup=warmup,
                                 alpha=alpha, min_sigma_frac=min_sigma_frac,
                                 direction=direction)
            for key, direction in _normalize_watch(watch).items()}
        self._lock = threading.Lock()
        self._seq = 0
        self.anomalies = 0        # anomalous STEPS (one per step, not per key)
        self.bundles_dropped = 0  # bundles skipped past max_bundles
        self.bundle_paths: list[str] = []
        # optional zero-arg callable returning cumulative fault counters
        # (RemoteRollout.fault_counters) folded into every bundle
        self.counters_fn = None
        # optional zero-arg callable returning the fleet flight-deck view
        # (PoolManager.engine_section) — written as engine.json so the
        # bundle shows per-engine occupancy/page pressure at anomaly time
        self.engine_fn = None
        # optional zero-arg callable returning the training health view
        # (TrainingHealthLedger.bundle_view) — written as training.json so
        # an entropy-collapse bundle carries the RL-dynamics tail and the
        # last batch's GRPO group table
        self.training_fn = None
        # optional zero-arg callable returning the recent per-step
        # critical paths (the trainer's CriticalPath.to_dict deque) —
        # written as critical_path.json so a stall bundle shows which
        # chain bounded the steps leading into the anomaly
        self.critical_path_fn = None
        # optional zero-arg callable returning the KV memory plane view
        # (PageLedger.snapshot via the engine/pool) — written as
        # memory.json so a cold-frac / headroom anomaly bundle carries the
        # page roles, tiers, free-cause churn and reconciliation state
        self.memory_fn = None
        # optional zero-arg callable returning the fleet engine-loop
        # profiler view (PoolManager.loop_profile_section) — written as
        # engine_profile.json so a device-frac/accounting-frac anomaly
        # bundle carries the per-engine device-vs-host split
        self.engine_profile_fn = None

    # -- step stream ---------------------------------------------------------

    def record_step(self, step: int, record: dict) -> str | None:
        """Feed one finished step's metric record; dumps and returns a
        bundle path when any watched series is anomalous."""
        with self._lock:
            self._steps.append({"step": step, **record})
        reasons = []
        for key, det in self._detectors.items():
            if key not in record:
                continue
            z = det.observe(float(record[key]))
            if z is not None:
                reasons.append(f"{key}={record[key]:.4g} z={z:.1f}")
        if not reasons:
            return None
        self.anomalies += 1
        return self.dump("anomaly", detail="; ".join(reasons), step=step)

    def counters(self) -> dict[str, float]:
        """Step-record gauges (``obs/*`` namespace, lint-documented)."""
        return {"obs/anomalies": float(self.anomalies),
                "obs/bundles": float(len(self.bundle_paths))}

    # -- bundle dump ---------------------------------------------------------

    def dump(self, reason: str, detail: str = "",
             step: int | None = None) -> str | None:
        """Write one post-mortem bundle; returns its path (None when the
        bundle budget is spent or the write fails — the recorder must
        never take the run down)."""
        with self._lock:
            if len(self.bundle_paths) >= self.max_bundles:
                self.bundles_dropped += 1
                log.warning("flight recorder: bundle budget (%d) spent; "
                            "dropping %r", self.max_bundles, reason)
                return None
            self._seq += 1
            seq = self._seq
            steps = list(self._steps)
        slug = re.sub(r"[^a-zA-Z0-9_.-]+", "_", reason)[:40]
        path = os.path.join(self.out_dir, "postmortem", f"{seq:03d}-{slug}")
        try:
            os.makedirs(path, exist_ok=True)
            from polyrl_tpu.obs import get_tracer
            from polyrl_tpu.obs.trace import clock_anchor

            tracer = get_tracer()
            with open(os.path.join(path, "spans.jsonl"), "w") as f:
                # leading monotonic↔wall anchor: the bundle's spans merge
                # skew-free with other processes' dumps (trace2perfetto)
                f.write(json.dumps(clock_anchor()) + "\n")
                for rec in tracer.records():
                    f.write(json.dumps(rec) + "\n")
            with open(os.path.join(path, "steps.jsonl"), "w") as f:
                for rec in steps:
                    f.write(json.dumps(rec) + "\n")
            with open(os.path.join(path, "stacks.txt"), "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
            counters = {}
            if self.counters_fn is not None:
                try:
                    counters = dict(self.counters_fn())
                except Exception:  # noqa: BLE001 — counters are best-effort
                    log.exception("flight recorder counters_fn failed")
            if self.engine_fn is not None:
                try:
                    engine_view = dict(self.engine_fn())
                except Exception:  # noqa: BLE001 — best-effort like counters
                    log.exception("flight recorder engine_fn failed")
                    engine_view = {}
                if engine_view:
                    with open(os.path.join(path, "engine.json"), "w") as f:
                        json.dump(engine_view, f, indent=2)
            if self.training_fn is not None:
                try:
                    training_view = dict(self.training_fn())
                except Exception:  # noqa: BLE001 — best-effort like counters
                    log.exception("flight recorder training_fn failed")
                    training_view = {}
                if training_view:
                    with open(os.path.join(path, "training.json"), "w") as f:
                        json.dump(training_view, f, indent=2)
            if self.critical_path_fn is not None:
                try:
                    cp_view = dict(self.critical_path_fn())
                except Exception:  # noqa: BLE001 — best-effort like counters
                    log.exception("flight recorder critical_path_fn failed")
                    cp_view = {}
                if cp_view:
                    with open(os.path.join(path, "critical_path.json"),
                              "w") as f:
                        json.dump(cp_view, f, indent=2)
            if self.memory_fn is not None:
                try:
                    memory_view = dict(self.memory_fn())
                except Exception:  # noqa: BLE001 — best-effort like counters
                    log.exception("flight recorder memory_fn failed")
                    memory_view = {}
                if memory_view:
                    with open(os.path.join(path, "memory.json"), "w") as f:
                        json.dump(memory_view, f, indent=2)
            if self.engine_profile_fn is not None:
                try:
                    profile_view = dict(self.engine_profile_fn())
                except Exception:  # noqa: BLE001 — best-effort like counters
                    log.exception("flight recorder engine_profile_fn failed")
                    profile_view = {}
                if profile_view:
                    with open(os.path.join(path, "engine_profile.json"),
                              "w") as f:
                        json.dump(profile_view, f, indent=2)
            try:
                # device memory profile: only real backends serve one (the
                # CPU test backend raises / returns nothing useful) — any
                # failure here must not cost the rest of the bundle
                import jax
                prof = jax.profiler.device_memory_profile()
                if prof and jax.default_backend() != "cpu":
                    with open(os.path.join(path, "memprof.pprof"), "wb") as f:
                        f.write(prof)
            except Exception:  # noqa: BLE001 — profile is best-effort
                log.debug("flight recorder: no device memory profile",
                          exc_info=True)
            with open(os.path.join(path, "counters.json"), "w") as f:
                json.dump({
                    "reason": reason,
                    "detail": detail,
                    "step": step,
                    "time_unix_s": time.time(),
                    "anomalies": self.anomalies,
                    "tracer_dropped_spans": tracer.dropped,
                    "fault_counters": counters,
                    "detectors": {k: d.state()
                                  for k, d in self._detectors.items()},
                }, f, indent=2)
        except Exception:  # noqa: BLE001 — a post-mortem writer that
            # crashes the run it is documenting is worse than no bundle
            log.exception("flight recorder bundle write failed (%s)", path)
            return None
        self.bundle_paths.append(path)
        log.warning("flight recorder: %s bundle -> %s (%s)",
                    reason, path, detail or "no detail")
        return path

    # -- signal wiring (main-thread only; train.py entry) --------------------

    def install_signal_handlers(self) -> None:
        """Dump a bundle on SIGTERM, then re-deliver the default action so
        the process still dies with the expected signal semantics. Call
        from the MAIN thread only (signal module constraint)."""

        def _on_term(signum, frame):  # noqa: ARG001
            self.dump("sigterm", detail=f"signal {signum}")
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_term)
