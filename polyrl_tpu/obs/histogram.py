"""Fixed-bucket log2 histograms + a process-global observation registry.

The reference systems this repo reproduces attribute their async/dataflow
wins to per-phase, per-request timing *distributions* (MindSpeed RL /
LlamaRL, PAPERS.md) — a per-step average hides exactly the tail a balancer
must react to. ``Histogram`` trades precision for O(1) memory and merges:
buckets are geometric with ``SUBDIV`` sub-buckets per octave (width
``2**(1/SUBDIV)`` ≈ 9%), so p50/p95/p99 come back within one bucket width
of the exact quantile; ``max`` is tracked exactly.
"""

from __future__ import annotations

import math
import threading

# sub-buckets per power of two: relative resolution 2**(1/8)-1 ≈ 9.05%
SUBDIV = 8
# fixed index range: values clamp into [2^-40, 2^40] (~1e-12 .. ~1e12) —
# anything outside is a unit bug, not a latency
_IDX_MIN = -40 * SUBDIV
_IDX_MAX = 40 * SUBDIV


class Histogram:
    """Log2-bucketed distribution: counts per fixed geometric bucket plus
    exact count/sum/min/max. Non-positive observations are counted but only
    contribute to count/sum/min (there is no log bucket for them)."""

    __slots__ = ("buckets", "count", "total", "vmin", "vmax", "zeros")

    def __init__(self):
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.zeros = 0  # observations <= 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        if v <= 0.0:
            self.zeros += 1
            return
        idx = min(max(math.floor(math.log2(v) * SUBDIV), _IDX_MIN), _IDX_MAX)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def observe_many(self, values) -> None:
        """Bulk observe: one numpy pass instead of a python loop — the
        training health ledger (obs/rlhealth.py) feeds thousands of
        per-token samples per step. Bucket math identical to
        :meth:`observe` (pinned by test). numpy imported lazily so the
        module stays import-light for the no-numpy consumers."""
        import numpy as np

        vals = np.asarray(values, np.float64).ravel()
        if vals.size == 0:
            return
        self.count += int(vals.size)
        self.total += float(vals.sum())
        self.vmin = min(self.vmin, float(vals.min()))
        self.vmax = max(self.vmax, float(vals.max()))
        pos = vals[vals > 0.0]
        self.zeros += int(vals.size - pos.size)
        if pos.size:
            idx = np.clip(np.floor(np.log2(pos) * SUBDIV),
                          _IDX_MIN, _IDX_MAX).astype(np.int64)
            uniq, counts = np.unique(idx, return_counts=True)
            for i, n in zip(uniq.tolist(), counts.tolist()):
                self.buckets[i] = self.buckets.get(i, 0) + n

    def merge(self, other: "Histogram") -> None:
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        self.zeros += other.zeros

    def percentile(self, q: float) -> float:
        """q in [0, 100]. Returns the geometric midpoint of the bucket the
        rank falls in, clamped to the exact observed [min, max]."""
        if self.count == 0:
            return 0.0
        rank = q / 100.0 * self.count
        seen = self.zeros
        if rank <= seen:  # the quantile sits in the non-positive mass
            return max(min(0.0, self.vmax), self.vmin)
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen >= rank:
                mid = 2.0 ** ((idx + 0.5) / SUBDIV)
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self, prefix: str) -> dict[str, float]:
        """Flat step-record keys: ``<prefix>/{p50,p95,p99,max,mean,count}``."""
        if self.count == 0:
            return {}
        return {
            f"{prefix}/p50": self.percentile(50.0),
            f"{prefix}/p95": self.percentile(95.0),
            f"{prefix}/p99": self.percentile(99.0),
            f"{prefix}/max": self.vmax,
            f"{prefix}/mean": self.mean,
            f"{prefix}/count": float(self.count),
        }


# -- process-global registry -------------------------------------------------
# Producers that have no handle on the trainer's per-step MetricsTracker
# (rollout engines, transfer agents, the manager client) observe here; the
# trainer drains the registry into each step record (one consumer).

_REG: dict[str, Histogram] = {}
_REG_LOCK = threading.Lock()


def observe(name: str, value: float) -> None:
    with _REG_LOCK:
        hist = _REG.get(name)
        if hist is None:
            hist = _REG[name] = Histogram()
        hist.observe(value)


def drain_histograms() -> dict[str, Histogram]:
    """Snapshot-and-reset the registry (each step record owns its window)."""
    with _REG_LOCK:
        out = dict(_REG)
        _REG.clear()
    return out
