"""Span tracer: thread-local context, bounded ring buffer, Perfetto export.

Span model (ARCHITECTURE.md "Observability"):

- a *trace* is one logical operation followed across threads and processes
  (one training step, one rollout batch); all its spans share ``trace_id``.
- a *span* is one timed phase with a ``span_id`` and a ``parent_id``;
  nesting comes from a thread-local span stack, so ``with span(...)``
  blocks compose without plumbing.
- context crosses threads via ``capture()``/``adopt()`` and processes via
  the ``X-Trace-Id``/``X-Span-Id`` HTTP headers (``Tracer.headers()``);
  the C++ manager echoes the pair into the requests it forwards, so a
  rollout server adopts the trainer's trace for its engine spans.

Memory is bounded: finished spans land in a ``deque(maxlen=max_spans)``
ring buffer (oldest evicted, ``dropped`` counts evictions) — a tracer left
on for a week-long run costs a fixed few MB, never an OOM.

Export is Chrome trace-event JSON (the format Perfetto/chrome://tracing
load directly): ``export_run()`` writes ``spans.jsonl`` (raw records, one
per line — the cross-process merge input for tools/trace2perfetto.py) and
``trace.json`` next to the run's JSONL metrics.

Clock model: spans stamp ``ts_us`` from the wall clock (cross-process
alignment) but ``dur_us`` AND ``ts_mono_us`` from the monotonic clock
(an NTP step mid-span must not corrupt durations or same-process
ordering). Each ``spans.jsonl`` leads with one ``clock_anchor`` record —
``{"type": "clock_anchor", "pid", "wall_us", "mono_us"}``, both clocks
sampled at the same instant — so a merger (:func:`chrome_trace`,
tools/trace2perfetto.py) can place every span at
``wall_us - (mono_us - span.ts_mono_us)``: monotonic spacing within a
process, wall alignment across processes, immune to clock steps between
the stamps.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
import uuid

_SEQ = itertools.count(1)


def _new_span_id() -> str:
    # unique across processes: pid + per-process counter
    return f"{os.getpid():x}.{next(_SEQ):x}"


class Tracer:
    def __init__(self, enabled: bool = False, max_spans: int = 4096,
                 out_dir: str | None = None):
        self.enabled = enabled
        self.out_dir = out_dir
        self.dropped = 0
        self._buf: collections.deque = collections.deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._tls = threading.local()

    # -- context ------------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> tuple[str, str] | None:
        """(trace_id, span_id) of the innermost open span on THIS thread,
        falling back to an adopted remote/cross-thread context."""
        st = self._stack()
        if st:
            return st[-1][0], st[-1][1]
        return getattr(self._tls, "adopted", None)

    def capture(self) -> tuple[str, str] | None:
        """Snapshot the current context for hand-off to another thread."""
        return self.current()

    @contextlib.contextmanager
    def adopt(self, ctx: tuple[str, str] | None):
        """Parent subsequent spans on this thread under ``ctx`` (a
        ``capture()`` result or a propagated (trace_id, span_id) pair).
        No-op for None or when disabled."""
        if not self.enabled or ctx is None:
            yield
            return
        prev = getattr(self._tls, "adopted", None)
        self._tls.adopted = (str(ctx[0]), str(ctx[1]))
        try:
            yield
        finally:
            self._tls.adopted = prev

    def headers(self) -> dict[str, str]:
        ctx = self.current()
        if not self.enabled or ctx is None:
            return {}
        return {"X-Trace-Id": ctx[0], "X-Span-Id": ctx[1]}

    # -- spans --------------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield None
            return
        parent = self.current()
        trace_id = parent[0] if parent else uuid.uuid4().hex[:16]
        span_id = _new_span_id()
        st = self._stack()
        st.append((trace_id, span_id))
        t0_wall = time.time()
        t0 = time.monotonic()
        error = ""
        try:
            yield span_id
        except BaseException as exc:
            error = repr(exc)
            raise
        finally:
            st.pop()
            rec = {
                "name": name,
                "trace_id": trace_id,
                "span_id": span_id,
                "parent_id": parent[1] if parent else "",
                "ts_us": int(t0_wall * 1e6),
                "ts_mono_us": int(t0 * 1e6),
                "dur_us": int((time.monotonic() - t0) * 1e6),
                "pid": os.getpid(),
                "tid": threading.get_ident() & 0xFFFFFFFF,
            }
            if attrs:
                rec["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
            if error:
                rec["error"] = error
            with self._lock:
                if len(self._buf) == self._buf.maxlen:
                    self.dropped += 1
                self._buf.append(rec)

    # -- buffer management --------------------------------------------------

    @property
    def max_spans(self) -> int:
        return self._buf.maxlen or 0

    def set_capacity(self, max_spans: int) -> None:
        with self._lock:
            self._buf = collections.deque(self._buf, maxlen=max_spans)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    # -- export -------------------------------------------------------------

    def export_run(self, out_dir: str | None = None) -> tuple[str, str] | None:
        """Dump ``spans.jsonl`` + Perfetto-loadable ``trace.json`` into
        ``out_dir`` (falls back to the configured one); None when there is
        nowhere to write."""
        out_dir = out_dir or self.out_dir
        if not out_dir:
            return None
        os.makedirs(out_dir, exist_ok=True)
        records = self.records()
        # ONE anchor for both artifacts: the jsonl leads with it and the
        # inline chrome trace is placed on it, so the two dumps agree
        anchor = clock_anchor()
        jsonl = os.path.join(out_dir, "spans.jsonl")
        with open(jsonl, "w") as f:
            f.write(json.dumps(anchor) + "\n")
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        trace = os.path.join(out_dir, "trace.json")
        with open(trace, "w") as f:
            json.dump(chrome_trace([anchor] + records), f)
        return jsonl, trace


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else repr(v)


def clock_anchor() -> dict:
    """One process's monotonic↔wall pairing, both clocks sampled now —
    the per-process alignment record leading every ``spans.jsonl``."""
    return {"type": "clock_anchor", "pid": os.getpid(),
            "wall_us": int(time.time() * 1e6),
            "mono_us": int(time.monotonic() * 1e6)}


def is_clock_anchor(rec: dict) -> bool:
    return rec.get("type") == "clock_anchor"


def chrome_trace(records: list[dict]) -> dict:
    """Span records → Chrome trace-event JSON (Perfetto/chrome://tracing).
    Spans become ``ph:"X"`` complete events; trace/span/parent ids ride in
    ``args`` so Perfetto's query view can join across processes.

    ``clock_anchor`` records are consumed, not emitted: a span carrying
    ``ts_mono_us`` whose process has an anchor is placed at
    ``anchor.wall_us - (anchor.mono_us - ts_mono_us)`` — monotonic
    spacing within the process, anchored to the wall for cross-process
    alignment, so merged timelines survive a wall-clock step between the
    span stamp and the export. Spans without an anchor (or predating
    ``ts_mono_us``) keep their raw wall ``ts_us``."""
    anchors = {rec["pid"]: rec for rec in records if is_clock_anchor(rec)}
    events = []
    pids = {}
    for rec in records:
        if is_clock_anchor(rec):
            continue
        pids.setdefault(rec["pid"], None)
        args = {"trace_id": rec["trace_id"], "span_id": rec["span_id"],
                "parent_id": rec.get("parent_id", "")}
        args.update(rec.get("attrs", {}))
        if rec.get("error"):
            args["error"] = rec["error"]
        anchor = anchors.get(rec["pid"])
        if anchor is not None and "ts_mono_us" in rec:
            ts = anchor["wall_us"] - (anchor["mono_us"] - rec["ts_mono_us"])
        else:
            ts = rec["ts_us"]
        events.append({
            "name": rec["name"],
            "cat": rec["name"].split("/", 1)[0],
            "ph": "X",
            "ts": ts,
            "dur": rec["dur_us"],
            "pid": rec["pid"],
            "tid": rec["tid"],
            "args": args,
        })
    for pid in pids:
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"polyrl pid {pid}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER
