"""Training health plane: the RL-dynamics ledger (ARCHITECTURE.md
"Training health plane").

The systems plane is fully observable (traces, goodput, engine flight
deck) but the *algorithmic* plane was scalars-only: ``actor/approx_kl``,
``actor/entropy`` and a TIS weight mean — an entropy collapse, a KL
blowup, or a batch full of zero-advantage GRPO groups stayed invisible
until the reward curves died. The ledger turns each training step's
already-computed arrays into distributions and group diagnostics:

- **distributions** (log2 :class:`~polyrl_tpu.obs.histogram.Histogram`,
  emitted as ``training/<name>/{p50,p95,p99,max,mean,count}``):
  ``training/adv_abs`` (|advantage| over masked tokens),
  ``training/tis_weight`` (per-token truncated importance weights),
  ``training/logprob_delta_abs`` (|old − rollout| logprob disagreement),
  ``training/response_len`` (per trajectory), and ``training/staleness``
  — the per-token weight-version lag (current push version minus the
  version that sampled the token, from the wire-carried
  ``output_token_weight_versions``). The staleness ledger is what the
  fully-async (``trainer.staleness_limit`` k>1) pipeline trains against:
  per-token TIS over mixed-version sequences is tuned by exactly this
  distribution, and the mixed-version TIS pass feeds back
  ``training/tis_unknown_version_tokens`` (tokens excluded from
  correction because their version is unknown) plus per-version-lag
  ``training/tis_weight_mean/lag<k>`` / ``training/tis_clip_frac/lag<k>``
  gauges.
- **GRPO group diagnostics** (gauges): ``training/degenerate_group_frac``
  (zero-reward-variance groups — their advantages are identically 0, the
  batch fraction that teaches nothing), ``training/effective_batch_frac``
  (trajectories with any nonzero masked advantage),
  ``training/truncated_frac`` / ``training/empty_response_frac`` (budget
  exhaustion / dropped-abort holes), and per-data-source reward
  ``training/reward_mean/<src>`` + ``training/reward_std/<src>``.
- **mirrors** (gauges): ``training/{entropy,approx_kl,grad_norm,
  tis_clip_frac}`` copied from the step's actor metrics so the
  FlightRecorder's direction-aware watch and the /statusz ``training``
  section read one namespace.

The ledger is fed per ibatch from ``StreamRLTrainer._process_ibatch``
(arrays it already holds — no extra device work) and finalized once per
step; a bounded tail of per-step rows plus the last batch's group table
back the /statusz ``training`` section and the flight recorder's
``training.json`` post-mortem bundles. Thread-safe: the statusz exporter
snapshots from its HTTP thread while the fit loop accounts.
"""

from __future__ import annotations

import collections
import re
import threading

from polyrl_tpu.obs.histogram import Histogram

_MISSING = object()
_SLUG_RE = re.compile(r"[^a-z0-9_.]+")

# per-step histogram names (emitted under training/<name>)
HIST_NAMES = ("adv_abs", "tis_weight", "logprob_delta_abs",
              "response_len", "staleness")

# step-metric mirrors: training/<out> <- first present actor key. One
# namespace for the health plane: the recorder watch, statusz section and
# bench extras all read training/* without knowing actor internals.
MIRRORS = (
    ("entropy", ("actor/entropy", "actor/entropy_rollout")),
    ("approx_kl", ("actor/approx_kl",)),
    ("grad_norm", ("actor/grad_norm",)),
    ("tis_weight_mean", ("actor/tis_weight_mean",)),
    ("tis_clip_frac", ("actor/tis_clip_frac",)),
)


def _slug(source) -> str:
    """Data-source name → metric-key segment (lowercase [a-z0-9_.])."""
    s = _SLUG_RE.sub("_", str(source or "default").lower()).strip("_")
    return s or "default"


class TrainingHealthLedger:
    """Per-step RL-dynamics accounting: observe per-ibatch arrays, finalize
    once per step into ``training/*`` gauges + histograms, keep a bounded
    tail for /statusz and post-mortem bundles."""

    def __init__(self, tail_steps: int = 64, max_group_rows: int = 64,
                 max_sources: int = 16):
        self.tail_steps = tail_steps
        self.max_group_rows = max_group_rows
        self.max_sources = max_sources
        self.steps = 0
        self.tail: collections.deque = collections.deque(maxlen=tail_steps)
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}
        self._last_groups: list[dict] = []
        self._reset_window()

    def _reset_window(self) -> None:
        self._hists = {name: Histogram() for name in HIST_NAMES}
        self._adv_n = 0
        self._adv_sum = 0.0
        self._adv_sumsq = 0.0
        self._adv_zero = 0
        self._groups = 0
        self._groups_degenerate = 0
        self._traj = 0
        self._traj_effective = 0
        self._traj_truncated = 0
        self._traj_empty = 0
        self._tok_masked = 0
        self._tok_known_version = 0
        self._tok_stale = 0
        self._staleness_max = 0
        # mixed-version TIS accounting (trainer passes the stats dict from
        # core_algos.mixed_version_importance_weights): unknown-version
        # tokens excluded from correction, and per-version-lag raw sums
        # lag -> [tokens, weight_sum, clipped]
        self._tis_seen = False
        self._tis_unknown = 0
        self._tis_lag: dict[int, list] = {}
        self._lp_delta_sum = 0.0
        self._lp_delta_n = 0
        # per-source reward moments: slug -> [n, sum, sumsq]
        self._sources: dict[str, list[float]] = {}
        self._group_rows: list[dict] = []

    # -- per-ibatch feed ----------------------------------------------------

    def observe_ibatch(self, *, advantages, response_mask, group_ids,
                       traj_rewards, data_sources=None,
                       old_log_probs=None, rollout_log_probs=None,
                       tis_weights=None, tis_stats=None,
                       weight_versions=None,
                       current_version=None,
                       max_response_length: int = 0) -> None:
        """Fold one processed ibatch into the current step window. All
        arguments are host numpy arrays the trainer already computed —
        ``weight_versions`` is the per-token ``rollout_weight_versions``
        tensor (−1 = version unknown on that token) and
        ``current_version`` the rollout plane's current push version the
        lag is measured against."""
        import numpy as np

        adv = np.asarray(advantages, np.float64)
        mask = np.asarray(response_mask, np.float64) > 0
        gids = np.asarray(group_ids).ravel()
        rewards = np.asarray(traj_rewards, np.float64).ravel()
        lens = mask.sum(axis=-1)
        tok_adv = adv[mask]
        eff = (np.abs(np.where(mask, adv, 0.0)).max(axis=-1) > 1e-12
               if adv.size else np.zeros(0, bool))

        with self._lock:
            h = self._hists
            h["adv_abs"].observe_many(np.abs(tok_adv))
            h["response_len"].observe_many(lens)
            self._adv_n += int(tok_adv.size)
            self._adv_sum += float(tok_adv.sum())
            self._adv_sumsq += float((tok_adv * tok_adv).sum())
            self._adv_zero += int((np.abs(tok_adv) <= 1e-12).sum())
            self._tok_masked += int(mask.sum())
            self._traj += int(len(rewards))
            self._traj_effective += int(eff.sum())
            if max_response_length > 0:
                self._traj_truncated += int((lens >= max_response_length).sum())
            self._traj_empty += int((lens == 0).sum())

            if old_log_probs is not None and rollout_log_probs is not None:
                delta = (np.asarray(old_log_probs, np.float64)
                         - np.asarray(rollout_log_probs, np.float64))[mask]
                h["logprob_delta_abs"].observe_many(np.abs(delta))
                self._lp_delta_sum += float(delta.sum())
                self._lp_delta_n += int(delta.size)

            if tis_weights is not None:
                h["tis_weight"].observe_many(
                    np.asarray(tis_weights, np.float64)[mask])

            if tis_stats is not None:
                # mixed-version TIS breakdown: unknown-version exclusions
                # (training/tis_unknown_version_tokens) and per-lag
                # weight/clip sums (training/tis_{weight_mean,
                # clip_frac}/lag<k> at finalize)
                self._tis_seen = True
                self._tis_unknown += int(tis_stats.get("unknown_tokens", 0))
                for lag, row in (tis_stats.get("per_lag") or {}).items():
                    agg = self._tis_lag.setdefault(int(lag), [0, 0.0, 0])
                    agg[0] += int(row["tokens"])
                    agg[1] += float(row["weight_sum"])
                    agg[2] += int(row["clipped"])

            if weight_versions is not None and current_version is not None:
                wv = np.asarray(weight_versions)
                known = mask & (wv >= 0)
                lag = np.maximum(int(current_version) - wv[known], 0)
                h["staleness"].observe_many(lag)
                self._tok_known_version += int(known.sum())
                self._tok_stale += int((lag > 0).sum())
                if lag.size:
                    self._staleness_max = max(self._staleness_max,
                                              int(lag.max()))

            # group table: reward spread, response shape and staleness per
            # GRPO group — the "what was this batch made of" view the
            # post-mortem bundle carries
            srcs = (list(data_sources) if data_sources is not None
                    else [""] * len(rewards))
            for g in np.unique(gids):
                sel = gids == g
                r = rewards[sel]
                degenerate = bool(r.size < 2 or (r.max() - r.min()) <= 1e-9)
                self._groups += 1
                self._groups_degenerate += int(degenerate)
                if len(self._group_rows) < self.max_group_rows:
                    glens = lens[sel]
                    row = {
                        "group": int(g), "size": int(r.size),
                        "reward_mean": round(float(r.mean()), 4),
                        "reward_std": round(float(r.std()), 4),
                        "degenerate": degenerate,
                        "len_mean": round(float(glens.mean()), 1),
                        "truncated": int((glens >= max_response_length).sum())
                        if max_response_length > 0 else 0,
                        "data_source": str(srcs[int(np.argmax(sel))] or ""),
                    }
                    if weight_versions is not None and \
                            current_version is not None:
                        gv = np.asarray(weight_versions)[sel]
                        gk = (np.asarray(response_mask)[sel] > 0) & (gv >= 0)
                        row["staleness_max"] = (
                            int(max(int(current_version) - gv[gk].min(), 0))
                            if gk.any() else 0)
                    self._group_rows.append(row)

            for src, rew in zip(srcs, rewards):
                slug = _slug(src)
                if slug not in self._sources and \
                        len(self._sources) >= self.max_sources:
                    slug = "other"
                mom = self._sources.setdefault(slug, [0.0, 0.0, 0.0])
                mom[0] += 1
                mom[1] += float(rew)
                mom[2] += float(rew) * float(rew)

    # -- per-step close -----------------------------------------------------

    def finalize_step(self, step: int, metrics=None
                      ) -> tuple[dict[str, float], dict[str, Histogram]]:
        """Close the step window: returns ``(gauges, histograms)`` for the
        step record (``metrics`` is the step's MetricsTracker, read for the
        actor-metric mirrors), appends the compact tail row, and resets
        the window for the next step."""
        with self._lock:
            gauges: dict[str, float] = {}
            n = max(self._adv_n, 1)
            mean = self._adv_sum / n
            var = max(self._adv_sumsq / n - mean * mean, 0.0)
            gauges["training/adv_mean"] = mean
            gauges["training/adv_std"] = var ** 0.5
            gauges["training/adv_zero_frac"] = self._adv_zero / n
            gauges["training/degenerate_group_frac"] = (
                self._groups_degenerate / self._groups if self._groups
                else 0.0)
            gauges["training/groups"] = float(self._groups)
            traj = max(self._traj, 1)
            gauges["training/effective_batch_frac"] = (
                self._traj_effective / traj)
            gauges["training/truncated_frac"] = self._traj_truncated / traj
            gauges["training/empty_response_frac"] = self._traj_empty / traj
            gauges["training/logprob_delta_mean"] = (
                self._lp_delta_sum / self._lp_delta_n
                if self._lp_delta_n else 0.0)
            tok = max(self._tok_masked, 1)
            gauges["training/staleness_known_frac"] = (
                self._tok_known_version / tok)
            gauges["training/staleness_frac_stale"] = (
                self._tok_stale / self._tok_known_version
                if self._tok_known_version else 0.0)
            gauges["training/staleness_max"] = float(self._staleness_max)
            if self._tis_seen:
                gauges["training/tis_unknown_version_tokens"] = float(
                    self._tis_unknown)
                for lag in sorted(self._tis_lag):
                    n, ws, cl = self._tis_lag[lag]
                    if n:
                        gauges[f"training/tis_weight_mean/lag{lag}"] = ws / n
                        gauges[f"training/tis_clip_frac/lag{lag}"] = cl / n
            for slug, (cnt, tot, sq) in self._sources.items():
                smean = tot / cnt
                gauges[f"training/reward_mean/{slug}"] = smean
                gauges[f"training/reward_std/{slug}"] = (
                    max(sq / cnt - smean * smean, 0.0) ** 0.5)
            if metrics is not None:
                for out, keys in MIRRORS:
                    for key in keys:
                        v = metrics.get(key, _MISSING)
                        if v is not _MISSING:
                            gauges[f"training/{out}"] = float(v)
                            break
            hists = {f"training/{name}": hist
                     for name, hist in self._hists.items() if hist.count}

            row = {"step": int(step)}
            for short, key in (
                    ("entropy", "training/entropy"),
                    ("approx_kl", "training/approx_kl"),
                    ("grad_norm", "training/grad_norm"),
                    ("tis_clip_frac", "training/tis_clip_frac"),
                    ("degenerate_group_frac",
                     "training/degenerate_group_frac"),
                    ("effective_batch_frac",
                     "training/effective_batch_frac"),
                    ("adv_std", "training/adv_std"),
                    ("staleness_max", "training/staleness_max"),
                    ("staleness_frac_stale",
                     "training/staleness_frac_stale")):
                if key in gauges:
                    row[short] = round(gauges[key], 6)
            st = self._hists["staleness"]
            if st.count:
                row["staleness_p95"] = round(st.percentile(95.0), 3)
            if self._sources:
                tot_n = sum(m[0] for m in self._sources.values())
                tot_s = sum(m[1] for m in self._sources.values())
                row["reward_mean"] = round(tot_s / max(tot_n, 1), 4)
            self.tail.append(row)
            self.steps += 1
            self._last = dict(gauges)
            if self._group_rows:
                self._last_groups = list(self._group_rows)
            self._reset_window()
            return gauges, hists

    # -- views (statusz / post-mortem) --------------------------------------

    def snapshot(self) -> dict:
        """The /statusz ``training`` section: last finalized gauges + a
        short trend tail (full tail + group table live in bundle_view)."""
        with self._lock:
            return {"steps": self.steps,
                    "last": dict(self._last),
                    "tail": list(self.tail)[-16:]}

    def bundle_view(self) -> dict:
        """``training.json`` for flight-recorder bundles: the full ledger
        tail plus the last batch's group table."""
        with self._lock:
            return {"steps": self.steps,
                    "last": dict(self._last),
                    "tail": list(self.tail),
                    "last_groups": list(self._last_groups)}
