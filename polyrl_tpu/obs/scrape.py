"""Prometheus text-exposition parsing for the manager /metrics scrape.

The C++ manager (and the rollout servers) already expose Prometheus text;
the trainer scrapes the manager once per step and merges the unlabeled
series into the step record as ``manager/*`` gauges — pool health, queue
depths, and per-route request totals become greppable next to the
training metrics instead of needing a separate Prometheus deployment.
"""

from __future__ import annotations


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Unlabeled ``name value`` series → {name: value}. Labeled series
    (``name{...}``) are per-instance breakdowns whose label values (raw
    endpoints) don't fit the flat ``area/name`` step-record namespace —
    they stay on the /metrics surface for real scrapers."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        if not name or "{" in name:
            continue
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def manager_gauges(text: str, strip: str = "polyrl_mgr_",
                   prefix: str = "manager/") -> dict[str, float]:
    """Scraped manager metrics → step-record gauge keys
    (``polyrl_mgr_running_reqs`` → ``manager/running_reqs``)."""
    out = {}
    for name, value in parse_prometheus_text(text).items():
        if name.startswith(strip):
            name = name[len(strip):]
        out[prefix + name] = value
    return out
