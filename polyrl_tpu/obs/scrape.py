"""Prometheus text-exposition parsing for the manager /metrics scrape.

The C++ manager (and the rollout servers) already expose Prometheus text;
the trainer scrapes the manager once per step and merges the unlabeled
series into the step record as ``manager/*`` gauges — pool health, queue
depths, and per-route request totals become greppable next to the
training metrics instead of needing a separate Prometheus deployment.

Parse telemetry rides the ``obs/*`` self-telemetry namespace: lines that
LOOK like samples but fail to parse (truncated response mid-line, a NaN
an exporter leaked, a value torn by a non-atomic writer) are COUNTED, not
silently dropped — ``RemoteRollout`` accumulates them behind the
``obs/scrape_partial`` step counter, and each scrape's wall latency lands
in the ``manager/scrape_s`` histogram.
"""

from __future__ import annotations


def parse_prometheus_text_partial(text: str) -> tuple[dict[str, float], int]:
    """Unlabeled ``name value`` series → ``({name: value}, partials)``.

    ``partials`` counts sample-looking lines that failed to parse — a
    missing or malformed value. Labeled series (``name{...}``) are NOT
    partial: they are per-instance breakdowns whose label values (raw
    endpoints) don't fit the flat ``area/name`` step-record namespace —
    they stay on the /metrics surface for real scrapers.
    """
    out: dict[str, float] = {}
    partials = 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        if not name or "{" in name:
            continue
        try:
            out[name] = float(value)
        except ValueError:
            partials += 1
            continue
    return out, partials


def parse_prometheus_text(text: str) -> dict[str, float]:
    """:func:`parse_prometheus_text_partial` keeping only the series."""
    return parse_prometheus_text_partial(text)[0]


def manager_gauges_partial(text: str, strip: str = "polyrl_mgr_",
                           prefix: str = "manager/"
                           ) -> tuple[dict[str, float], int]:
    """Scraped manager metrics → (step-record gauge keys, partial-line
    count): ``polyrl_mgr_running_reqs`` → ``manager/running_reqs``."""
    out = {}
    series, partials = parse_prometheus_text_partial(text)
    for name, value in series.items():
        if name.startswith(strip):
            name = name[len(strip):]
        out[prefix + name] = value
    return out, partials


def manager_gauges(text: str, strip: str = "polyrl_mgr_",
                   prefix: str = "manager/") -> dict[str, float]:
    """:func:`manager_gauges_partial` keeping only the gauges."""
    return manager_gauges_partial(text, strip=strip, prefix=prefix)[0]
