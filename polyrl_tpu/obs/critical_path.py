"""Per-step critical-path extraction over the span ring
(ARCHITECTURE.md "Critical-path & time-series plane").

The goodput ledger answers "how much wall did each phase COST"; this
module answers "which chain of spans actually BOUNDED the step". The
difference matters exactly when the pipeline overlaps lanes (OPPO in
PAPERS.md): with ``pipeline_depth>=1`` the producer lane can spend 0.8 s
generating while the foreground only BLOCKS 0.3 s of it — phase walls
then rank ``update`` above ``generate`` even though speeding the update
changes nothing. The extractor reconstructs the step's span tree from
the tracer ring (the ``trainer/step`` root, its same-trace children, the
``trainer/prefetch`` producer lane joined on its ``step`` attr — the
lane thread starts before any step span exists, so it owns its own
trace_id — and cross-process engine/manager spans joined on trace_id)
and sweeps the step window:

- every elementary interval is attributed to the **innermost foreground
  span** covering it (nested spans win, so colocated generation inside
  the ibatch wait reads ``generate``, not ``bubble``);
- a blocked interval (``trainer/ibatch_wait`` with no nested work) is
  attributed to ``generate`` when the producer lane's prefetch span
  covers it — the trainer is waiting ON generation — and to ``bubble``
  only when nothing anywhere is producing;
- the segment walls therefore partition the step wall exactly: their sum
  reconciles with ``goodput/step_wall_s`` by construction (pinned <=5%,
  like the goodput ledger's own attribution).

Per segment the extractor also totals the **hidden** time (span time
inside the window that the sweep did NOT surface — generation running
under the update phases). ``critical + hidden`` is the segment's full
chain length, and:

- ``bottleneck``   — the segment with the longest chain (argmax of
  totals; a fully-hidden 0.8 s generation outranks a 0.5 s update);
- ``slack_s``      — the tightest competitor's slack: min over the other
  active segments of ``wall - total(seg)`` — how much the bottleneck can
  improve before that phase binds instead;
- ``headroom_s``   — "if the bottleneck sped up 10%, the step wall drops
  by X": ``min(0.10 * total(bottleneck), slack_s)``.

Emitted as ``critpath/*`` step gauges (``bottleneck`` is the float index
into :data:`SEGMENTS` — the metrics plane is numeric), kept as dicts for
``critical_path.json`` flight-recorder bundles and tools/fleet_report.py.
Import-light; pure function of the span records.
"""

from __future__ import annotations

SEGMENTS = ("generate", "process", "update", "push", "bubble", "manager",
            "housekeeping", "other")

ROOT_SPAN = "trainer/step"
LANE_SPAN = "trainer/prefetch"
WAIT_SPAN = "trainer/ibatch_wait"

# exact span-name -> segment (the marked_timer foreground phases plus the
# producer lane); names absent here fall through to the prefix rules.
# These are SPAN names, not metric keys — built under the "trainer/"
# span prefix here rather than written out so the metric-name lint's
# metric-dict heuristic (tools/check_metric_names.py) stays quiet.
_NAME_SEGMENT = {"trainer/" + phase: seg for phase, seg in {
    "gen": "generate",
    "reward": "process",
    "old_log_prob": "process",
    "ref_log_prob": "process",
    "values": "process",
    "adv": "process",
    "remax_baseline": "process",
    "broadcast": "process",
    "update_actor": "update",
    "update_critic": "update",
    "update_weight": "push",
    "prefetch_fence": "push",
    "testing": "housekeeping",
    "save_checkpoint": "housekeeping",
}.items()}
_NAME_SEGMENT[LANE_SPAN] = "generate"
_PREFIX_SEGMENT = (
    ("rollout/", "generate"),   # remote stream rounds
    ("engine/", "generate"),    # engine-side spans (cross-process)
    ("manager/", "manager"),    # control-plane round trips
    ("transfer/", "push"),      # weight-fabric pack/wire/push
)


def classify(name: str) -> str | None:
    """Span name -> segment (None for spans outside the taxonomy —
    including the wait span, which is attributed by what covers it)."""
    if name == WAIT_SPAN:
        return None
    seg = _NAME_SEGMENT.get(name)
    if seg is not None:
        return seg
    for prefix, seg in _PREFIX_SEGMENT:
        if name.startswith(prefix):
            return seg
    return None


def _t0_us(rec: dict) -> int:
    # prefer the monotonic stamp (same-process comparisons survive wall-
    # clock steps); spans.jsonl predating it still carries ts_us
    return int(rec.get("ts_mono_us", rec.get("ts_us", 0)))


def _merged_len(intervals: list[tuple[int, int]]) -> int:
    """Total length of the union of [a, b) intervals."""
    total = 0
    end = None
    for a, b in sorted(intervals):
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


class CriticalPath:
    """One step's attribution: segment walls (``critical_s`` — partition
    of the step wall), hidden chain time, the ordered path, and the
    bottleneck/slack/headroom summary."""

    def __init__(self, *, step: int | None, wall_s: float,
                 critical_s: dict[str, float], hidden_s: dict[str, float],
                 path: list[tuple[str, float]], remote: list[dict]):
        self.step = step
        self.wall_s = wall_s
        self.critical_s = critical_s
        self.hidden_s = hidden_s
        self.path = path
        self.remote = remote
        self.total_s = {seg: critical_s.get(seg, 0.0) + hidden_s.get(seg, 0.0)
                        for seg in SEGMENTS}
        # argmax of chain totals; SEGMENTS order breaks exact ties
        self.bottleneck = max(SEGMENTS, key=lambda s: self.total_s[s])
        others = [self.wall_s - self.total_s[seg] for seg in SEGMENTS
                  if seg != self.bottleneck and self.total_s[seg] > 0.0]
        self.slack_s = max(0.0, min(others)) if others else self.wall_s
        self.headroom_s = max(0.0, min(
            0.10 * self.total_s[self.bottleneck], self.slack_s))

    def metrics(self) -> dict[str, float]:
        """``critpath/*`` step gauges (all-float: ``bottleneck`` is the
        index into :data:`SEGMENTS`)."""
        wall = max(self.wall_s, 1e-9)
        out = {
            "critpath/wall_s": self.wall_s,
            "critpath/bottleneck": float(SEGMENTS.index(self.bottleneck)),
            "critpath/bottleneck_frac": self.total_s[self.bottleneck] / wall,
            "critpath/slack_s": self.slack_s,
            "critpath/headroom_s": self.headroom_s,
        }
        for seg in SEGMENTS:
            out[f"critpath/{seg}_frac"] = self.critical_s.get(seg, 0.0) / wall
        return out

    def to_dict(self) -> dict:
        """JSON view (``critical_path.json`` bundles, fleet_report)."""
        return {
            "step": self.step,
            "wall_s": round(self.wall_s, 6),
            "bottleneck": self.bottleneck,
            "slack_s": round(self.slack_s, 6),
            "headroom_s": round(self.headroom_s, 6),
            "critical_s": {k: round(v, 6)
                           for k, v in self.critical_s.items() if v > 0.0},
            "hidden_s": {k: round(v, 6)
                         for k, v in self.hidden_s.items() if v > 0.0},
            "path": [[seg, round(dur, 6)] for seg, dur in self.path],
            "remote": self.remote,
        }


def extract_critical_path(records: list[dict], *, step: int | None = None,
                          wall_s: float | None = None,
                          max_remote: int = 16) -> CriticalPath | None:
    """Extract one step's critical path from raw span records
    (``Tracer.records()`` or a parsed ``spans.jsonl``).

    ``step`` selects the ``trainer/step`` root by its ``step`` attr (the
    LAST match wins — a warmup fit's ring leftovers don't shadow the live
    run); None takes the latest root. ``wall_s`` is the step's full
    goodput wall (the root span ends before validation/checkpoint/scrape,
    so the window is extended to the wall and the trailing housekeeping
    spans attribute); None falls back to the root span's own duration.
    Returns None when no matching root exists (tracing off, ring evicted).
    """
    roots = [r for r in records if r.get("name") == ROOT_SPAN]
    if step is not None:
        roots = [r for r in roots
                 if (r.get("attrs") or {}).get("step") == step]
    if not roots:
        return None
    root = max(roots, key=_t0_us)
    t0 = _t0_us(root)
    root_dur = int(root.get("dur_us", 0))
    wall_us = max(root_dur, int(wall_s * 1e6) if wall_s else 0, 1)
    t1 = t0 + wall_us

    pid, tid = root.get("pid"), root.get("tid")
    trace_ids = {root.get("trace_id")}
    fg: list[tuple[int, int, str]] = []      # (start, end, name), clipped
    lane: list[tuple[int, int]] = []         # producer prefetch intervals
    by_seg: dict[str, list[tuple[int, int]]] = {s: [] for s in SEGMENTS}
    remote: list[dict] = []

    for rec in records:
        if rec is root:
            continue
        s0 = _t0_us(rec)
        s1 = s0 + int(rec.get("dur_us", 0))
        a, b = max(s0, t0), min(s1, t1)
        if a >= b:
            continue
        name = str(rec.get("name", ""))
        if rec.get("pid") != pid:
            # cross-process chain members, joined on the step's trace ids
            if rec.get("trace_id") in trace_ids:
                remote.append({"name": name, "pid": rec.get("pid"),
                               "dur_s": round((s1 - s0) / 1e6, 6),
                               "span_id": rec.get("span_id", "")})
            continue
        if name == LANE_SPAN:
            trace_ids.add(rec.get("trace_id"))
            lane.append((a, b))
            by_seg["generate"].append((a, b))
            continue
        seg = classify(name)
        if seg is not None:
            by_seg[seg].append((a, b))
        if rec.get("tid") == tid and (seg is not None or name == WAIT_SPAN):
            fg.append((a, b, name))

    # elementary-interval sweep over the foreground boundaries: innermost
    # covering span wins; a bare wait is generate when the lane covers it
    bounds = sorted({t0, t1} | {x for a, b, _ in fg for x in (a, b)
                    if t0 <= x <= t1})
    lane_sorted = sorted(lane)
    path: list[tuple[str, int]] = []
    for a, b in zip(bounds, bounds[1:]):
        if a >= b:
            continue
        mid = (a + b) // 2
        covering = [(sa, sb, nm) for sa, sb, nm in fg if sa <= mid < sb]
        if covering:
            # innermost = latest start (ties: earliest end — the smaller
            # span is the deeper one)
            sa, sb, nm = max(covering, key=lambda s: (s[0], -s[1]))
            seg = classify(nm)
            if seg is None:  # the wait span: blocked — on whom?
                seg = "generate" if any(la <= mid < lb
                                        for la, lb in lane_sorted) \
                    else "bubble"
        else:
            seg = "other"
        if path and path[-1][0] == seg:
            path[-1] = (seg, path[-1][1] + (b - a))
        else:
            path.append((seg, b - a))

    critical_us = {s: 0.0 for s in SEGMENTS}
    for seg, dur in path:
        critical_us[seg] += dur
    critical_s = {s: v / 1e6 for s, v in critical_us.items()}
    hidden_s = {
        seg: max(0.0, _merged_len(ivals) / 1e6 - critical_s[seg])
        for seg, ivals in by_seg.items() if ivals}
    remote.sort(key=lambda r: -r["dur_s"])
    step_attr = (root.get("attrs") or {}).get("step", step)
    return CriticalPath(
        step=step_attr if isinstance(step_attr, int) else step,
        wall_s=wall_us / 1e6,
        critical_s=critical_s, hidden_s=hidden_s,
        path=[(seg, dur / 1e6) for seg, dur in path],
        remote=remote[:max_remote])
