"""/statusz — the live health plane (ARCHITECTURE.md "Goodput & health
plane").

One ``curl :port/statusz`` answers "what is this plane doing right now":
both the trainer and the rollout server serve the SAME JSON schema
(:func:`build_snapshot`), so a pool-wide sweep needs one parser. The
trainer mounts a standalone :class:`StatuszServer` (it has no HTTP surface
of its own); the rollout server mounts ``/statusz`` as a route on its
existing listener (rollout/server.py).

Schema (``polyrl/statusz/v8`` — additive evolution only; v2 added the
``engine`` section, v3 the ``training`` section, v4 the ``timeseries``
section, v5 the ``autoscale`` section, v6 the ``memory`` section, v7 the
``spill`` block inside ``memory`` (host-RAM KV spill tier), v8 the
``loop`` block inside ``engine`` (engine-loop profiler);
version-history table in ARCHITECTURE.md "Observability"):

- ``role``      — ``trainer`` | ``rollout``
- ``pid`` / ``time_unix_s`` / ``uptime_s``
- ``step``      — current training step (trainer; null on rollout)
- ``goodput``   — cumulative phase attribution (GoodputLedger.snapshot)
- ``histograms``— latest-window quantiles ``{name: {p50,p95,p99,max,
  mean,count}}``
- ``counters``  — cumulative fault/salvage/anomaly counters
- ``gauges``    — scalar last-values (weight staleness, queue depth, ...)
- ``queues``    — engine/pipeline queue depths
- ``weights``   — weight version / push count / staleness
- ``pool``      — elastic-pool membership (engines + lifecycle counts;
  trainer role with a PoolManager attached, empty elsewhere)
- ``engine``    — the engine flight deck (rollout/flightdeck.py): request
  lifecycle tails (TTFT/TPOT/queue wait), slot occupancy, page-pool
  utilization, token-accounting reconciliation. Rollout role serves its
  own ledger; trainer role serves the fleet aggregate from PoolManager
  sweeps; empty elsewhere. Since v8 it ALWAYS carries a ``loop`` block
  (obs/engine_profile.py): exhaustive per-iteration phase attribution of
  the engine loop's wall (``attributed_frac`` pinned to 1.0,
  goodput-ledger style), per-phase log2 latency summaries, and the
  windowed device-vs-host split (``device_frac`` /
  ``host_overhead_frac`` / ``accounting_frac`` / ``idle_frac``).
  ``{"enabled": false}`` when ``rollout.loop_profile`` is off or the
  engine has no loop profiler; the trainer's is the fleet view keyed by
  instance.
- ``training``  — the training health plane (obs/rlhealth.py): last
  finalized ``training/*`` gauges (entropy/KL mirrors, degenerate-group
  fraction, per-token weight-version staleness) plus a short per-step
  trend tail. Trainer role with a TrainingHealthLedger attached (the
  default); empty on the rollout plane.
- ``timeseries`` — the fleet time-series rail (obs/timeseries.py):
  windowed per-key aggregates (last/mean/p95/min/max + least-squares
  slope) over the recent step snapshots — goodput phase walls, pool and
  fleet ``engine/*`` gauges, ``training/*`` and ``critpath/*`` scalars.
  The trainer windows its step records; the rollout server windows its
  ``server_info`` samples (one per manager stats poll / statusz hit).
- ``autoscale`` — the closed-loop autoscaling plane (rollout/autoscale.py):
  last decision (action, reason, inputs, suppressions), the degradation
  tier, the fleet envelope, and cumulative action totals. Trainer role
  with an AutoscaleController attached; empty elsewhere (including the
  rollout plane — the controller lives trainer-side).
- ``memory``    — the KV memory plane (rollout/kvledger.py): per-page
  role counts (free / active-decode / published / preref-held),
  hot/warm/cold residency tiers, churn + free-cause counters,
  page-lifetime histograms, the ledger↔pool ``attributed_frac``
  reconciliation block, and HBM truth (used/headroom/unaccounted).
  Since v7 it also carries a ``spill`` block when the host-RAM KV spill
  tier is on (rollout/kvspill.py): spilled page/byte totals, cumulative
  spill/restore traffic, the windowed restore rate (thrash signal), and
  the host pool's lane/capacity stats. Rollout role serves its engine's
  ledger; trainer role serves the fleet worst-case aggregate from
  PoolManager sweeps; empty elsewhere (and with
  ``rollout.kv_ledger=false``).

Every v8 section is ALWAYS present on both planes (conformance-tested) so
consumers never need existence checks.

``GET /metrics`` on the same listener renders the snapshot's numeric
leaves as Prometheus text (``polyrl_statusz_*`` gauges) for real scrapers.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

log = logging.getLogger(__name__)

SCHEMA = "polyrl/statusz/v8"
_PROC_T0 = time.monotonic()
_HIST_SUFFIXES = ("p50", "p95", "p99", "max", "mean", "count")

# every key the schema guarantees on EVERY snapshot, both planes — the
# conformance contract consumers (and the conformance test) rely on
REQUIRED_SECTIONS = ("schema", "role", "pid", "time_unix_s", "uptime_s",
                     "step", "goodput", "histograms", "counters", "gauges",
                     "queues", "weights", "pool", "engine", "training",
                     "timeseries", "autoscale", "memory")


def build_snapshot(role: str, *, step: int | None = None,
                   goodput: dict | None = None,
                   histograms: dict | None = None,
                   counters: dict | None = None,
                   gauges: dict | None = None,
                   queues: dict | None = None,
                   weights: dict | None = None,
                   pool: dict | None = None,
                   engine: dict | None = None,
                   training: dict | None = None,
                   timeseries: dict | None = None,
                   autoscale: dict | None = None,
                   memory: dict | None = None) -> dict:
    """The shared statusz schema; every section present (empty when the
    plane has nothing for it) so consumers never need existence checks."""
    return {
        "schema": SCHEMA,
        "role": role,
        "pid": os.getpid(),
        "time_unix_s": round(time.time(), 3),
        "uptime_s": round(time.monotonic() - _PROC_T0, 3),
        "step": step,
        "goodput": goodput or {},
        "histograms": histograms or {},
        "counters": counters or {},
        "gauges": gauges or {},
        "queues": queues or {},
        "weights": weights or {},
        "pool": pool or {},
        "engine": engine or {},
        "training": training or {},
        "timeseries": timeseries or {},
        "autoscale": autoscale or {},
        "memory": memory or {},
    }


def nest_histograms(record: dict) -> dict:
    """Flat step-record histogram keys (``name/p50`` ... ``name/count``) →
    the statusz nested form ``{name: {p50: v, ...}}``."""
    out: dict[str, dict[str, float]] = {}
    for key, value in record.items():
        base, _, suffix = key.rpartition("/")
        if base and suffix in _HIST_SUFFIXES:
            out.setdefault(base, {})[suffix] = value
    # a genuine histogram emits the full summary; a lone */max gauge (say)
    # is not one — require the count marker the summary always carries
    return {k: v for k, v in out.items() if "count" in v}


def prometheus_text(snapshot: dict, prefix: str = "polyrl_statusz") -> str:
    """Numeric leaves of the snapshot as Prometheus gauges (full precision;
    path segments joined by ``_`` with non-metric chars squashed)."""
    lines: list[str] = []

    def emit(path: str, value) -> None:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        name = re.sub(r"[^a-zA-Z0-9_]", "_", f"{prefix}_{path}")
        lines.append(f"# TYPE {name} gauge")
        val = (str(int(value)) if float(value).is_integer()
               else repr(float(value)))
        lines.append(f"{name} {val}")

    def walk(path: str, node) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{path}_{k}" if path else str(k), v)
        else:
            emit(path, node)

    walk("", snapshot)
    return "\n".join(lines) + "\n"


class StatuszServer:
    """Tiny stdlib HTTP exporter: ``provider()`` is called per request and
    must return a :func:`build_snapshot` dict. A provider failure answers
    500 with the error — the exporter must never take the plane down."""

    def __init__(self, provider: Callable[[], dict],
                 host: str = "127.0.0.1", port: int = 0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.split("?", 1)[0] in ("/statusz", "/"):
                    code, snap = outer._snapshot()
                    self._send(code, json.dumps(snap).encode(),
                               "application/json")
                elif self.path == "/metrics":
                    code, snap = outer._snapshot()
                    self._send(code, prometheus_text(snap).encode(),
                               "text/plain; version=0.0.4")
                elif self.path == "/health":
                    self._send(200, b'{"status": "ok"}', "application/json")
                else:
                    self._send(404, json.dumps(
                        {"error": f"no route {self.path}"}).encode(),
                        "application/json")

        self._provider = provider
        self._http = ThreadingHTTPServer((host, port), Handler)
        self.port = self._http.server_address[1]
        self.endpoint = f"{host}:{self.port}"
        self._thread: threading.Thread | None = None

    def _snapshot(self) -> tuple[int, dict]:
        try:
            return 200, self._provider()
        except Exception as exc:  # noqa: BLE001 — exporter never kills a run
            log.exception("statusz provider failed")
            return 500, {"schema": SCHEMA, "error": repr(exc)}

    def start(self) -> "StatuszServer":
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        name="statusz", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
