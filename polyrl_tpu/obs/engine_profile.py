"""Engine-loop profiler: exhaustive per-iteration phase attribution for
the CB engine's loop thread (ARCHITECTURE.md "Engine-loop profiler").

PRs 7/12/17/18 piled flight-deck, KV-ledger and spill-sweep bookkeeping
onto the engine loop; the only record of where a dispatch's wall went was
a private cumulative ``_trace`` dict that never left the process. This
module is the rollout-side analogue of the trainer's goodput ledger
(obs/goodput.py): every loop iteration's wall is decomposed into an
exhaustive, NON-OVERLAPPING phase taxonomy whose sum equals the iteration
wall by construction (the residual lands in ``other``), so
``attributed_frac`` reads exactly like the goodput ledger's — the named
phases over the wall, > 1.0 meaning double-counted attribution.

Phase taxonomy (seconds, exclusive self-time):

- ``collect_wave``  — admission wave assembly (slot+page reservation,
  prefix-cache match, group fork bookkeeping)
- ``restore``       — spill readmit: host→device KV restore of spilled
  prefix pages (rollout/kvspill.py restore-then-attach)
- ``prefill_dispatch`` — prefill/attach/chunk dispatch calls (host wall
  spent in the dispatch enqueue + any synchronous device wait inside it)
- ``decode_dispatch_device`` — device-state upload + the fused-k step
  dispatch (the device wait inside the decode hot path)
- ``sample_fetch``  — loop thread blocked on the fetcher's batched
  ``device_get`` (plus the dead-fetcher synchronous fallback)
- ``emit``          — streaming fetched tokens to request queues, host
  mirror updates, finalize folds
- ``accounting``    — deck + KV-ledger + dispatch bookkeeping (the
  PR 7/17/18 overhead the regression budget pins)
- ``spill_sweep``   — watermark sweep page-out (host spill tier writes)
- ``idle``          — no work: queue waits and backoff sleeps
- ``other``         — the unattributed residual (clamped at 0)

Attribution is STACK-BASED with exclusive (self-time) semantics: the
engine nests phases freely (``_drain_emit_q`` runs inside admission,
``_spill_pages`` inside allocation pressure) and a nested phase's wall is
charged to the nested phase, never double-counted against its parent.
Stacks are thread-local, so the fetcher thread (or a unit test driving
engine internals directly) can enter phases without corrupting the loop
thread's iteration; cumulative totals fold under one lock.

The windowed device-vs-host split (``device_frac`` /
``host_overhead_frac`` / ``accounting_frac`` / ``idle_frac``) is computed
over a two-bucket flip window (~``window_s`` of recent loop wall) so a
long-lived engine reports CURRENT behaviour, not a run-lifetime average:

- ``device_frac``          = (prefill_dispatch + decode_dispatch_device +
  sample_fetch) / wall — host wall spent dispatching to or waiting on the
  device (the utilization ceiling the disaggregation work steers on);
- ``accounting_frac``      = (accounting + spill_sweep) / wall;
- ``host_overhead_frac``   = 1 − device_frac − idle_frac — ALL host-side
  work including the residual, so the three fracs + idle partition 1.

Per-dispatch spans for the dispatch phases are emitted into the process
tracer ring (obs/trace.py) when tracing is enabled, trace_id-joined with
whatever context the serving layer adopted — ``tools/trace2perfetto.py``
renders the engine-loop track beside the trainer's spans.

The legacy ``_trace``/``_tmark`` seam (POLYRL_CB_TRACE) is absorbed here:
:meth:`mark_legacy` keeps the cumulative ``{key: seconds, n_<key>}``
counters ``/metrics`` has always rendered, owned by the profiler instead
of a parallel dict.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time

from polyrl_tpu.obs.histogram import Histogram
from polyrl_tpu.obs.trace import get_tracer

PHASES = ("collect_wave", "restore", "prefill_dispatch",
          "decode_dispatch_device", "sample_fetch", "emit", "accounting",
          "spill_sweep", "idle", "other")
# host wall spent dispatching to / waiting on the device
DEVICE_PHASES = frozenset(
    ("prefill_dispatch", "decode_dispatch_device", "sample_fetch"))
# the bookkeeping overhead the regression budget pins
ACCOUNTING_PHASES = frozenset(("accounting", "spill_sweep"))
# phases worth a tracer span each occurrence (dispatch-scale, not µs-scale)
SPAN_PHASES = frozenset(
    ("prefill_dispatch", "decode_dispatch_device", "sample_fetch",
     "restore"))


class EngineLoopProfiler:
    """Exhaustive engine-loop phase attribution (module docstring).

    ``clock`` is injectable for fake-clock tests (the partition pin drives
    it deterministically so ``attributed_frac`` is exactly 1.0)."""

    def __init__(self, window_s: float = 20.0, clock=time.monotonic,
                 tracer=None):
        self._clock = clock
        self._tracer = tracer  # None → resolve the process tracer lazily
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.window_s = float(window_s)
        self.iters = 0
        self.wall_s = 0.0
        self.totals = {p: 0.0 for p in PHASES}
        self.counts = {p: 0 for p in PHASES}
        self.hists = {p: Histogram() for p in PHASES if p != "other"}
        # two-bucket flip window: [wall, device, accounting, idle] each;
        # readers sum both buckets → ~window_s/2..window_s of loop wall
        self._win_cur = [0.0, 0.0, 0.0, 0.0]
        self._win_prev = [0.0, 0.0, 0.0, 0.0]
        # legacy POLYRL_CB_TRACE counters (cumulative seconds + n_ counts);
        # the fetcher thread marks "fetch" concurrently with loop marks
        self._legacy: dict[str, float] = collections.defaultdict(float)

    # -- thread-local attribution state --------------------------------------

    def _state(self):
        st = getattr(self._tls, "state", None)
        if st is None:
            # stack of [phase_name, self_seconds]; mark = last event time;
            # iter_phases = per-iteration fold (loop thread only)
            st = self._tls.state = {"stack": [], "mark": None,
                                    "iter_phases": None, "iter_t0": None}
        return st

    def _attr(self, st, now: float) -> None:
        """Charge the wall since the last event to the innermost open
        phase (self-time). Time with an empty stack inside an iteration
        becomes the ``other`` residual at iteration close."""
        mark = st["mark"]
        if mark is not None and st["stack"]:
            st["stack"][-1][1] += now - mark
        st["mark"] = now

    # -- phases ---------------------------------------------------------------

    @contextlib.contextmanager
    def phase(self, name: str):
        st = self._state()
        self._attr(st, self._clock())
        st["stack"].append([name, 0.0])
        span_cm = None
        if name in SPAN_PHASES:
            tracer = self._tracer if self._tracer is not None \
                else get_tracer()
            if tracer.enabled:
                span_cm = tracer.span("engine/" + name)
                span_cm.__enter__()
        try:
            yield
        finally:
            if span_cm is not None:
                span_cm.__exit__(None, None, None)
            self._attr(st, self._clock())
            _name, self_s = st["stack"].pop()
            if st["iter_phases"] is not None:
                st["iter_phases"][name] = (
                    st["iter_phases"].get(name, 0.0) + self_s)
            with self._lock:
                self.totals[name] += self_s
                self.counts[name] += 1
                self.hists[name].observe(self_s)

    @contextlib.contextmanager
    def iteration(self):
        """One ``_loop_iter`` window: phases inside fold into the
        iteration's partition; the leftover wall (empty-stack time between
        phases) lands in ``other`` so the sum equals the iteration wall by
        construction."""
        st = self._state()
        t0 = self._clock()
        st["iter_phases"] = {}
        st["iter_t0"] = t0
        st["mark"] = t0
        try:
            yield
        finally:
            now = self._clock()
            self._attr(st, now)
            phases, st["iter_phases"] = st["iter_phases"], None
            st["iter_t0"] = None
            wall = now - t0
            attributed = sum(phases.values())
            other = max(0.0, wall - attributed)
            device = sum(phases.get(p, 0.0) for p in DEVICE_PHASES)
            acct = sum(phases.get(p, 0.0) for p in ACCOUNTING_PHASES)
            idle = phases.get("idle", 0.0)
            with self._lock:
                self.iters += 1
                self.wall_s += wall
                self.totals["other"] += other
                cur = self._win_cur
                cur[0] += wall
                cur[1] += device
                cur[2] += acct
                cur[3] += idle
                if cur[0] >= self.window_s / 2.0:
                    self._win_prev = cur
                    self._win_cur = [0.0, 0.0, 0.0, 0.0]

    # -- legacy POLYRL_CB_TRACE counters -------------------------------------

    def mark_legacy(self, key: str, dt: float) -> None:
        with self._lock:
            self._legacy[key] += dt
            self._legacy["n_" + key] += 1

    def legacy_report(self) -> dict:
        with self._lock:
            return dict(self._legacy)

    # -- export ---------------------------------------------------------------

    def attributed_frac(self) -> float:
        """Named-phase seconds over the iteration wall (goodput-ledger
        semantics): 1.0 when every iteration's wall is inside a phase,
        > 1.0 means double-counted attribution. 1.0 before any
        iteration."""
        with self._lock:
            if self.wall_s <= 0.0:
                return 1.0
            return (self.wall_s - self.totals["other"]) / self.wall_s

    def _window(self) -> tuple[float, float, float, float]:
        cur, prev = self._win_cur, self._win_prev
        return tuple(cur[i] + prev[i] for i in range(4))

    def window_fracs(self) -> dict:
        """The windowed device-vs-host split over ~window_s of recent
        loop wall; zeros before the first iteration closes."""
        with self._lock:
            wall, device, acct, idle = self._window()
        if wall <= 0.0:
            return {"wall_s": 0.0, "device_frac": 0.0,
                    "host_overhead_frac": 0.0, "accounting_frac": 0.0,
                    "idle_frac": 0.0}
        device_f = device / wall
        idle_f = idle / wall
        return {
            "wall_s": wall,
            "device_frac": device_f,
            # everything host-side that is neither device wait nor idle —
            # includes the unattributed residual, so the three partition 1
            "host_overhead_frac": max(0.0, 1.0 - device_f - idle_f),
            "accounting_frac": acct / wall,
            "idle_frac": idle_f,
        }

    def server_info_fields(self) -> dict:
        """Flat keys merged into ``server_info`` (no ``/`` — the C++
        manager's stats poller indexes them directly; the server's
        time-series feed prefixes them as ``engine/*``)."""
        w = self.window_fracs()
        return {
            "device_frac": round(w["device_frac"], 6),
            "host_overhead_frac": round(w["host_overhead_frac"], 6),
            "accounting_frac": round(w["accounting_frac"], 6),
            "loop_attributed_frac": round(self.attributed_frac(), 6),
        }

    def snapshot(self) -> dict:
        """The /statusz ``engine.loop`` block (both planes carry one; the
        trainer's is the fleet aggregate in rollout/pool.py)."""
        with self._lock:
            totals = dict(self.totals)
            counts = dict(self.counts)
            iters = self.iters
            wall = self.wall_s
            hists = {p: {
                "p50": h.percentile(50.0), "p95": h.percentile(95.0),
                "p99": h.percentile(99.0),
                "max": h.vmax if h.count else 0.0,
                "mean": h.mean, "count": float(h.count),
            } for p, h in self.hists.items() if h.count}
        out = {
            "enabled": True,
            "iters": iters,
            "wall_s": round(wall, 3),
            "attributed_frac": round(
                (wall - totals["other"]) / wall if wall > 0 else 1.0, 6),
            "phase_s": {p: round(v, 4) for p, v in totals.items()},
            "phase_frac": {p: round(v / wall, 4) if wall > 0 else 0.0
                           for p, v in totals.items()},
            "phase_n": {p: counts[p] for p in PHASES if counts[p]},
            "window": {k: round(v, 4)
                       for k, v in self.window_fracs().items()},
        }
        if hists:
            out["latency"] = hists
        return out
