"""Goodput accounting: decompose each training step's wall time into an
exhaustive, non-overlapping phase taxonomy (ARCHITECTURE.md "Goodput &
health plane").

The adaptive balancer is only as good as the time attribution feeding it:
before this ledger the manager saw ONE scalar (``perf/trainer_bubble_s``)
while the rest of a step's wall went unattributed — MindSpeed RL and
LlamaRL (PAPERS.md) both attribute their disaggregated-RL wins to
per-phase accounting across planes. The ledger consumes what the step
already measures (``marked_timer`` phase timings, the stream-wait bubble,
the pipeline overlap credit, the obs histogram registry) and emits
``goodput/*`` step metrics whose phase keys sum to the measured wall step
time by construction (the residual lands in ``goodput/other_s``), plus
tokens-per-chip-second and a model-FLOPs MFU estimate
(:mod:`polyrl_tpu.utils.flops` over the ``models/decoder.py`` shapes).

Phase taxonomy (seconds, non-overlapping, sum = ``goodput/step_wall_s``):

- ``generate``  — in-loop (colocated) generation (``timing_s/gen``)
- ``bubble``    — blocked waiting on rollout arrival, NET of the compute
  phases that run inside the wait (colocated gen + multi-host broadcast
  happen inside ``next(ibatch)`` and would double-count otherwise)
- ``process``   — reward / old+ref logprob / values / advantage / broadcast
- ``update``    — actor + critic fwd/bwd and optimizer steps
- ``weight_push`` — weight sync (``update_weight`` + the pipelined
  ``prefetch_fence``)
- ``salvage_resume`` — stream-resume recovery waits
  (``rollout/resume_wait_s`` observations)
- ``manager_rtt``  — manager control-plane round trips outside streaming
  (``manager/rtt_s`` observations)
- ``housekeeping`` — validation + checkpoint IO
- ``other``     — the unattributed residual (clamped at 0)

``goodput/overlap_credit_s`` (pipelined generation that happened before
the step began) is informational and deliberately NOT part of the sum —
it is time saved, not time spent.
"""

from __future__ import annotations

import threading

PHASES = ("generate", "bubble", "process", "update", "weight_push",
          "salvage_resume", "manager_rtt", "housekeeping", "other")

# marked_timer key -> phase. Keys absent here are still covered: they are
# inside the wall, so the residual ("other") absorbs them.
TIMING_PHASE = {
    "gen": "generate",
    "reward": "process",
    "old_log_prob": "process",
    "ref_log_prob": "process",
    "values": "process",
    "adv": "process",
    "remax_baseline": "process",
    "broadcast": "process",
    "update_actor": "update",
    "update_critic": "update",
    "update_weight": "weight_push",
    "prefetch_fence": "weight_push",
    "testing": "housekeeping",
    "save_checkpoint": "housekeeping",
}
# phases that execute INSIDE the ibatch wait (the bubble measures blocked
# time on next(ibatch); colocated generation and the multi-host broadcast
# run within that wait, so the bubble is netted down by their time)
_INSIDE_BUBBLE = ("gen", "broadcast")
# histogram-registry series whose per-step TOTAL is a phase
HIST_PHASE = {
    "rollout/resume_wait_s": "salvage_resume",
    "manager/rtt_s": "manager_rtt",
}


class GoodputLedger:
    """Per-step attribution ledger + cumulative run totals (the /statusz
    snapshot reads the cumulative side). Thread-safe: the statusz exporter
    snapshots from its own HTTP thread while the fit loop accounts."""

    def __init__(self, flops=None):
        # optional utils.flops.FlopsCounter for the MFU estimate
        self.flops = flops
        self.steps = 0
        self.cum = {p: 0.0 for p in PHASES}
        self.cum_wall = 0.0
        self.cum_overlap = 0.0
        self.cum_tokens = 0
        self.last: dict[str, float] = {}
        self._lock = threading.Lock()

    # -- per-step attribution ------------------------------------------------

    def account(self, *, step_time_s: float, timings: dict | None = None,
                bubble_s: float = 0.0, overlap_s: float = 0.0,
                histograms: dict | None = None, n_tokens: int = 0,
                mean_context_len: float = 0.0,
                n_chips: int = 1) -> dict[str, float]:
        """Attribute one step; returns the ``goodput/*`` metric dict.

        ``timings`` is the tracker's ``timing_s`` map (seconds per
        marked_timer key); ``histograms`` the step's drained obs registry
        (``{name: Histogram}`` — totals of the HIST_PHASE series become
        their phases). ``step_time_s`` is the FULL wall including
        validation/checkpoint, so housekeeping is attributable."""
        timings = timings or {}
        phases = {p: 0.0 for p in PHASES}
        for key, secs in timings.items():
            phase = TIMING_PHASE.get(key)
            if phase is not None:
                phases[phase] += float(secs)
        inside = sum(float(timings.get(k, 0.0)) for k in _INSIDE_BUBBLE)
        phases["bubble"] = max(0.0, float(bubble_s) - inside)
        for name, hist in (histograms or {}).items():
            phase = HIST_PHASE.get(name)
            if phase is not None:
                phases[phase] += float(hist.total)
        attributed = sum(phases.values())
        phases["other"] = max(0.0, float(step_time_s) - attributed)

        wall = max(float(step_time_s), 1e-9)
        out = {f"goodput/{p}_s": v for p, v in phases.items()}
        out["goodput/step_wall_s"] = float(step_time_s)
        out["goodput/overlap_credit_s"] = float(overlap_s)
        # fraction of the wall the named (non-residual) phases explain —
        # >1 means double-counted attribution, the bug the pinning test
        # exists to catch
        out["goodput/attributed_frac"] = attributed / wall
        out["goodput/productive_frac"] = (
            phases["generate"] + phases["process"] + phases["update"]) / wall
        if n_tokens:
            out["goodput/tok_s_per_chip"] = (
                n_tokens / wall / max(int(n_chips), 1))
        if self.flops is not None and n_tokens:
            # goodput/{tflops_all_chips,tflops_per_chip,mfu} from the model
            # flops decomposition (utils/flops.py over decoder shapes)
            out.update(self.flops.step_metrics(
                n_tokens, mean_context_len, float(step_time_s),
                prefix="goodput"))
        with self._lock:
            self.steps += 1
            for p, v in phases.items():
                self.cum[p] += v
            self.cum_wall += float(step_time_s)
            self.cum_overlap += float(overlap_s)
            self.cum_tokens += int(n_tokens)
            self.last = dict(out)
        return out

    # -- cumulative view (the /statusz goodput block) ------------------------

    def snapshot(self) -> dict:
        with self._lock:
            cum = dict(self.cum)
            return {
                "steps": self.steps,
                "wall_s": round(self.cum_wall, 3),
                "tokens": self.cum_tokens,
                "overlap_credit_s": round(self.cum_overlap, 3),
                "phase_s": {p: round(v, 3) for p, v in cum.items()},
                "phase_frac": {
                    p: round(v / self.cum_wall, 4) if self.cum_wall else 0.0
                    for p, v in cum.items()},
                "last": dict(self.last),
            }
